"""Compiled-artifact auditor: what did XLA *actually* build for a step?

PR 4's CompileGuard/SyncTally certify the serving invariants at the Python
trace level — but the ROADMAP's tensor-parallel arc needs those contracts
to survive sharding, and a sharded step can silently acquire implicit
all-gathers, resharding copies, or un-honored donation that no trace-level
check can see. This module reads the truth straight off the compiled
artifact, the way ``tools/aot_shard_proof.py`` already reads
``memory_analysis`` for training:

- **Collective census** — AOT-lower a step and walk the optimized HLO for
  ``all-reduce`` / ``all-gather`` / ``reduce-scatter`` /
  ``collective-permute`` / ``all-to-all`` instructions (sync and
  ``-start`` async forms; ``-done`` halves are not double-counted), each
  with its payload byte volume parsed from the result shape. The census is
  enforced against a declared :class:`CollectiveBudget` — a decode step on
  a single chip budgets ZERO, a tensor-parallel step budgets exactly the
  collectives its sharding implies.
- **Host-transfer check** — the compiled-level twin of SyncTally: flag
  ``infeed``/``outfeed``, host ``send``/``recv``, and host-callback
  ``custom-call``s (``xla_python_cpu_callback`` & friends) baked into a
  hot step. A trace-level tally can only see syncs the *host* initiates;
  this sees the ones the *program* performs.
- **Aliasing verification** — the compiled proof behind lint rule PT006:
  confirm XLA's ``input_output_alias`` table actually honors every
  ``donate_argnums`` leaf. A donated-but-copied KV pool silently holds two
  pools live (a 2x HBM cost no Python-level check can observe — jax still
  marks the donated buffer deleted either way).
- **Resource roll-up** — ``cost_analysis()`` flops and
  ``memory_analysis()`` peak bytes per step (arguments + temp arena +
  outputs − aliased), reported through ``serving_hlo_*`` metrics and the
  bench JSON.

:data:`REGISTRY` names the repo's auditable steps (the serving engine's
prefill/chunk/decode, the paged cache's swap/COW jits, the toy 8-device
``shard_map`` step that gated the sharded-serving arc, and the REAL
tensor-parallel serving steps it grew into — ``tp2_engine_*`` + the
per-shard cache movers, certified against the budgets the engine itself
declares);
``python -m paddle_tpu.analysis --hlo [--step NAME]`` sweeps them with
clean exit codes. ``ServingConfig(debug_checks=True)`` audits every engine
step once per compiled program (per prefill bucket + decode) at its first
trace — one extra AOT lower+compile per program, a debugging cost, never a
serving-path cost.

Like tracecheck, this module never imports the serving stack at module
level — serving imports us; the registry builders import serving lazily.
"""
from __future__ import annotations

import inspect
import os
import re
import warnings
from dataclasses import dataclass, field

__all__ = ["CollectiveBudget", "CollectiveOp", "HostTransfer",
           "HloAuditReport", "HloCheckError", "CollectiveBudgetError",
           "CollectiveOverlapError", "HostTransferError",
           "AliasingViolation", "SINGLE_CHIP", "census", "audit",
           "audit_guard", "StepSpec", "REGISTRY", "run_step", "main"]


class HloCheckError(RuntimeError):
    """A compiled-artifact audit failed."""


class CollectiveBudgetError(HloCheckError):
    """The compiled step issues more collective traffic than its declared
    CollectiveBudget. The message names the op kind, count, and bytes."""


class CollectiveOverlapError(HloCheckError):
    """The compiled step's async collectives do not overlap enough compute:
    fewer than ``min_overlap_frac`` of the ``-start``/``-done`` pairs have
    ANY instruction scheduled between them — the scheduler serialized the
    collective against the compute it was supposed to hide under."""


class HostTransferError(HloCheckError):
    """The compiled step contains host-transfer ops (infeed/outfeed/host
    callback) beyond its budget — a hidden device<->host stall per step."""


class AliasingViolation(HloCheckError):
    """XLA did not honor a donated buffer with input-output aliasing: the
    donated-and-deleted input is COPIED into its output, so two copies are
    live — for a pool-sized buffer, a silent 2x HBM cost."""


# --------------------------------------------------------------- HLO parsing
# element widths in BITS — sub-byte dtypes (s2/s4, the EQuARX-style
# quantized-collective payloads these byte volumes are the baseline for)
# must not round up per element, only per buffer
_DTYPE_BITS = {
    "pred": 8, "s2": 2, "u2": 2, "s4": 4, "u4": 4, "s8": 8, "u8": 8,
    "f8e5m2": 8, "f8e4m3": 8, "f8e4m3fn": 8, "f8e4m3b11fnuz": 8,
    "f8e5m2fnuz": 8, "f8e4m3fnuz": 8,
    "s16": 16, "u16": 16, "f16": 16, "bf16": 16,
    "s32": 32, "u32": 32, "f32": 32,
    "s64": 64, "u64": 64, "f64": 64, "c64": 64, "c128": 128,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")

# one HLO instruction: `%name = TYPE opcode(...)` where TYPE is a scalar/
# array type or a tuple `(t1, t2)` (tuple element types never nest parens)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<iname>[\w.\-]+)\s*=\s*"
    r"(?P<type>\([^=]*?\)|\S+)\s+(?P<op>[\w\-]+)\(")

_ALIAS_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(?:may|must)-alias\)")

# replica_groups in the explicit form `{{0,1},{2,3}}` (empty `{}` = one
# group of every participant) and the iota form `[2,2]<=[4]` (optionally
# transposed: `[2,2]<=[2,2]T(1,0)`) newer XLA emits for large meshes.
# collective-permute carries `source_target_pairs` instead — same `{{a,b}}`
# surface, pair semantics.
_GROUPS_RE = re.compile(
    r"(?:replica_groups|source_target_pairs)=\{((?:\{[\d,\s]*\},?\s*)*)\}")
_GROUP_RE = re.compile(r"\{([\d,\s]*)\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")

_TARGET_RE = re.compile(r'custom_call_target="([^"]*)"')

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all",
                    "collective-broadcast")

# host-callback custom-call targets (CPU + TPU spellings)
_HOST_TARGET_RE = re.compile(r"callback|host|infeed|outfeed", re.IGNORECASE)


def _shape_elem_bytes(type_str: str) -> list[int]:
    """Per-array-element byte volumes of an HLO type string. Layouts
    (``{1,0}``) and token/opaque elements contribute nothing."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(type_str):
        bits = _DTYPE_BITS.get(dtype)
        if bits is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append((n * bits + 7) // 8)
    return out


def _type_bytes(type_str: str) -> int:
    """Total byte volume of an HLO result type — ``f32[4,8]{1,0}`` or a
    tuple ``(f32[4]{0}, bf16[2,2]{1,0})``."""
    return sum(_shape_elem_bytes(type_str))


@dataclass(frozen=True)
class CollectiveOp:
    kind: str     # base opcode: all-reduce, all-gather, ...
    nbytes: int   # payload bytes parsed from the result type
    instr: str    # HLO instruction name (%...)
    line: str     # the instruction line, trimmed
    # async `-start`/`-done` pair (vs the sync single-instruction form)
    is_async: bool = False
    # overlap depth: instructions the scheduler placed between this
    # collective's -start and its -done — the compute it hides under.
    # Always 0 for sync collectives (nothing can interleave)
    overlap: int = 0
    # participant structure, parsed once here so --overlap and meshcheck
    # share a single HLO walk. For collective-permute these are the
    # (source, target) pairs; empty with group_count 0 means the
    # instruction named no groups (= one group of every participant).
    replica_groups: tuple = ()
    group_count: int = 0
    channel_id: int | None = None
    use_global_device_ids: bool = False


@dataclass(frozen=True)
class HostTransfer:
    kind: str    # infeed | outfeed | send | recv | custom-call
    detail: str  # custom_call_target for callbacks, else the opcode
    line: str


_REF_RE = re.compile(r"%([\w.\-]+)")


def _parse_replica_groups(raw: str) -> tuple[tuple, int]:
    """Decode the participant groups of one collective instruction line.
    Handles the explicit ``replica_groups={{0,1},{2,3}}`` form (and the
    same-surface ``source_target_pairs`` of collective-permute), plus the
    iota form ``replica_groups=[G,S]<=[d0,d1]T(p0,p1)``: ranks 0..prod(d)-1
    reshaped to ``[d0,d1,...]`` C-order, transposed by the permutation,
    flattened, and chunked into G groups of S. Returns (groups, count);
    ``((), 0)`` when the line names no groups at all."""
    m = _GROUPS_RE.search(raw)
    if m is not None:
        groups = tuple(
            tuple(int(x) for x in g.split(",") if x.strip())
            for g in _GROUP_RE.findall(m.group(1)))
        groups = tuple(g for g in groups if g)
        return groups, len(groups)
    m = _IOTA_GROUPS_RE.search(raw)
    if m is not None:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        perm = ([int(p) for p in m.group(4).split(",")] if m.group(4)
                else list(range(len(dims))))
        tdims = [dims[p] for p in perm]
        flat = []
        for pos in range(n_groups * group_size):
            # multi-index in the transposed shape, C-order
            tidx, rem = [], pos
            for d in reversed(tdims):
                tidx.append(rem % d)
                rem //= d
            tidx.reverse()
            # map back through the permutation and ravel in the original
            oidx = [0] * len(dims)
            for axis, t in zip(perm, tidx):
                oidx[axis] = t
            rank = 0
            for d, i in zip(dims, oidx):
                rank = rank * d + i
            flat.append(rank)
        groups = tuple(tuple(flat[g * group_size:(g + 1) * group_size])
                       for g in range(n_groups))
        return groups, n_groups
    return (), 0


def census(hlo_text: str) -> tuple[tuple[CollectiveOp, ...],
                                   tuple[HostTransfer, ...]]:
    """Walk optimized HLO text and collect (collectives, host transfers).
    Async ``-start``/``-done`` pairs count once (at the start), and each
    carries its OVERLAP depth: the number of instructions the scheduler
    placed between the ``-start`` and its matching ``-done`` — the compute
    the collective hides under. A ``-start`` immediately followed by its
    ``-done`` overlaps nothing (the async form bought no latency hiding),
    which is exactly what the latency-hiding-scheduler census exists to
    catch."""
    entries: list[dict] = []   # mutable while scanning (overlap counts)
    hosts: list[HostTransfer] = []
    open_starts: dict[str, int] = {}  # -start instr name -> entries index
    for raw in hlo_text.splitlines():
        m = _INSTR_RE.match(raw)
        if m is None:
            continue
        op = m.group("op")
        line = raw.strip()[:200]
        if op.endswith("-done") and op[:-5] in COLLECTIVE_KINDS:
            # close the start this done names (its operand): instructions
            # after this point no longer overlap that collective
            ref = _REF_RE.search(raw[m.end():])
            if ref is not None:
                open_starts.pop(ref.group(1), None)
            continue
        base = op[:-6] if op.endswith("-start") else op
        if base in COLLECTIVE_KINDS:
            # an async `-start` result is a tuple carrying operand AND
            # result buffers — ((op, res)) scalar form, ((op0..opN-1,
            # res0..resN-1)) when XLA's combiner merged N collectives.
            # Charge the result half only: the payload the sync form(s)
            # would report, so byte caps hold across sync/async/combined
            # compilation of the same traffic
            is_async = op.endswith("-start")
            elems = _shape_elem_bytes(m.group("type"))
            nbytes = (sum(elems[len(elems) // 2:])
                      if is_async and len(elems) > 1 else sum(elems))
            if is_async:
                open_starts[m.group("iname")] = len(entries)
            groups, group_count = _parse_replica_groups(raw)
            ch = _CHANNEL_RE.search(raw)
            entries.append(dict(
                kind=base, nbytes=nbytes, instr=m.group("iname"),
                line=line, is_async=is_async,
                replica_groups=groups, group_count=group_count,
                channel_id=int(ch.group(1)) if ch else None,
                use_global_device_ids="use_global_device_ids=true" in raw))
            continue
        # any other instruction scheduled while a -start is in flight is
        # work the collective overlaps (credited to every open start)
        for idx in open_starts.values():
            entries[idx]["overlap"] = entries[idx].get("overlap", 0) + 1
        if op in ("infeed", "outfeed"):
            hosts.append(HostTransfer(op, op, line))
        elif op in ("send", "recv") and "is_host_transfer=true" in raw:
            hosts.append(HostTransfer(op, op, line))
        elif op == "custom-call":
            t = _TARGET_RE.search(raw)
            if t is not None and _HOST_TARGET_RE.search(t.group(1)):
                hosts.append(HostTransfer("custom-call", t.group(1), line))
    return tuple(CollectiveOp(**e) for e in entries), tuple(hosts)


# ------------------------------------------------------------------ budgets
@dataclass(frozen=True)
class CollectiveBudget:
    """Per-step ceiling on compiled collective/host-transfer traffic. The
    default is the single-chip serving contract: ZERO everything — a
    sharded step declares exactly the collectives its partitioning implies
    (and optionally caps their total payload bytes)."""
    all_reduce: int = 0
    all_gather: int = 0
    reduce_scatter: int = 0
    collective_permute: int = 0
    all_to_all: int = 0
    collective_broadcast: int = 0
    host_transfers: int = 0
    max_collective_bytes: int | None = None
    # per-medium arms: byte/op caps split by the link each collective
    # rides — ICI (within a host) vs DCN (across hosts). Enforcement
    # needs a declared MeshTopology to classify each collective's axis,
    # so these are checked by meshcheck's MeshReport.check(), not by
    # HloAuditReport.enforce() (which stays topology-blind)
    max_ici_bytes: int | None = None
    max_dcn_bytes: int | None = None
    max_dcn_ops: int | None = None
    # minimum fraction of ASYNC collectives that must overlap at least one
    # instruction (latency-hiding-scheduler census). Enforced over async
    # `-start`/`-done` pairs ONLY: a backend that compiles everything to
    # sync collectives (CPU) has nothing to schedule and passes vacuously,
    # so the same budget certifies on a forced host mesh and on chip
    min_overlap_frac: float = 0.0

    def allowed(self, kind: str) -> int:
        return getattr(self, kind.replace("-", "_"), 0)


#: the single-chip serving contract: no collectives, no host transfers
SINGLE_CHIP = CollectiveBudget()


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB"):
        if abs(n) < 1024:
            return f"{n:.0f} {unit}" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.2f} GiB"


# ------------------------------------------------------------------- report
@dataclass(frozen=True)
class HloAuditReport:
    """Everything the compiled artifact admits about one jitted step."""
    name: str
    collectives: tuple[CollectiveOp, ...] = ()
    host_transfers: tuple[HostTransfer, ...] = ()
    donated_leaves: int = 0
    aliased_leaves: int = 0
    donated_bytes: int = 0
    alias_bytes: int = 0
    # donated leaf names with no alias entry; () when compiled-parameter
    # pruning makes the name mapping ambiguous (counts still enforced)
    unaliased: tuple[str, ...] = ()
    flops: float = 0.0
    argument_bytes: int = 0
    temp_bytes: int = 0
    output_bytes: int = 0
    peak_bytes: int = 0

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for c in self.collectives:
            out[c.kind] = out.get(c.kind, 0) + 1
        return out

    @property
    def collective_bytes(self) -> int:
        return sum(c.nbytes for c in self.collectives)

    @property
    def async_collectives(self) -> int:
        """Collectives compiled to the async -start/-done form."""
        return sum(1 for c in self.collectives if c.is_async)

    @property
    def overlapped_collectives(self) -> int:
        """Async collectives with at least one instruction scheduled
        between their -start and -done — actually hidden under compute."""
        return sum(1 for c in self.collectives
                   if c.is_async and c.overlap > 0)

    @property
    def overlap_frac(self) -> float:
        """overlapped / async collectives; 0.0 when the program has no
        async collectives (sync-only compilation overlaps nothing)."""
        n = self.async_collectives
        return self.overlapped_collectives / n if n else 0.0

    def enforce(self, budget: CollectiveBudget) -> "HloAuditReport":
        """Raise naming the offending op when the artifact exceeds the
        budget; aliasing of donated buffers is always enforced."""
        for kind, n in sorted(self.counts().items()):
            allowed = budget.allowed(kind)
            if n > allowed:
                first = next(c for c in self.collectives if c.kind == kind)
                raise CollectiveBudgetError(
                    f"hlocheck({self.name!r}): {kind} x{n} "
                    f"({_fmt_bytes(self.collective_bytes)} total collective "
                    f"payload) exceeds the declared budget of {allowed} — "
                    f"first over-budget op: {first.line}")
        if budget.max_collective_bytes is not None and \
                self.collective_bytes > budget.max_collective_bytes:
            raise CollectiveBudgetError(
                f"hlocheck({self.name!r}): total collective payload "
                f"{self.collective_bytes} bytes exceeds the declared cap of "
                f"{budget.max_collective_bytes} bytes "
                f"({', '.join(sorted(self.counts()))})")
        n_async = self.async_collectives
        if budget.min_overlap_frac > 0.0 and n_async and \
                self.overlap_frac < budget.min_overlap_frac:
            worst = next(c for c in self.collectives
                         if c.is_async and c.overlap == 0)
            raise CollectiveOverlapError(
                f"hlocheck({self.name!r}): only "
                f"{self.overlapped_collectives}/{n_async} async "
                f"collective(s) overlap any compute "
                f"(frac {self.overlap_frac:.2f} < declared minimum "
                f"{budget.min_overlap_frac:.2f}) — the scheduler "
                f"serialized -start against -done. First serialized op: "
                f"{worst.line}")
        if len(self.host_transfers) > budget.host_transfers:
            first = self.host_transfers[0]
            raise HostTransferError(
                f"hlocheck({self.name!r}): {len(self.host_transfers)} "
                f"host-transfer op(s) compiled into the step (budget "
                f"{budget.host_transfers}) — every one stalls the dispatch "
                f"pipeline mid-program. First: {first.kind} "
                f"({first.detail})")
        if self.donated_leaves and (
                self.aliased_leaves < self.donated_leaves
                or self.alias_bytes < self.donated_bytes):
            who = (f" — unaliased leaf/leaves: "
                   f"{', '.join(self.unaliased)}" if self.unaliased else "")
            raise AliasingViolation(
                f"hlocheck({self.name!r}): {self.donated_leaves} donated "
                f"leaf/leaves ({_fmt_bytes(self.donated_bytes)}) but the "
                f"compiled artifact aliases only {self.aliased_leaves} "
                f"({_fmt_bytes(self.alias_bytes)}) — a donated-but-copied "
                f"buffer holds TWO copies live (for a KV pool, a silent 2x "
                f"HBM cost){who}")
        return self

    def summary(self) -> str:
        c = self.counts()
        coll = ", ".join(f"{k}x{v}" for k, v in sorted(c.items())) or "none"
        alias = (f"{self.aliased_leaves}/{self.donated_leaves} donated "
                 f"aliased" if self.donated_leaves else "no donation")
        ov = (f"overlap {self.overlapped_collectives}/"
              f"{self.async_collectives} async"
              if self.async_collectives else "overlap n/a (sync)")
        return (f"hlocheck {self.name}: collectives {coll} "
                f"({_fmt_bytes(self.collective_bytes)}); {ov}; host "
                f"transfers {len(self.host_transfers)}; {alias}; "
                f"flops/step {self.flops:.4g}; peak HBM "
                f"{_fmt_bytes(self.peak_bytes)}")

    def overlap_summary(self) -> str:
        """The ``--overlap`` CLI view: one line per collective naming its
        compiled form (sync vs async) and the number of instructions the
        scheduler placed while it was in flight."""
        head = (f"hlocheck {self.name}: "
                f"{self.overlapped_collectives}/{self.async_collectives} "
                f"async collective(s) overlapped"
                if self.async_collectives else
                f"hlocheck {self.name}: all collectives compiled sync "
                f"(no async -start/-done pairs to overlap)")
        lines = [head]
        for c in self.collectives:
            form = "async" if c.is_async else "sync"
            lines.append(f"  {form:<5} {c.kind:<20} "
                         f"{_fmt_bytes(c.nbytes):>9}  overlap={c.overlap}"
                         f"  %{c.instr}")
        return "\n".join(lines)


# -------------------------------------------------------------------- audit
def _leaf_nbytes(leaf) -> int:
    """Per-DEVICE bytes of one argument leaf: for a sharded array, the
    shard each device actually holds — XLA's ``memory_analysis`` numbers
    (incl. ``alias_size_in_bytes``, which the donation check compares
    against) are all per-device, so a donated heads-sharded KV pool must
    be costed at pool/tp bytes or the aliasing check would demand more
    aliased bytes than any device owns."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shape = sharding.shard_shape(leaf.shape)
            n = leaf.dtype.itemsize
            for d in shape:
                n *= d
            return int(n)
        except Exception:  # noqa: BLE001 — fall back to the global size
            pass
    n = getattr(leaf, "nbytes", None)
    if n is not None:
        return int(n)
    return 0  # python scalar: negligible and never donated in practice


def audit(fn, args, *, name: str | None = None, static_argnums=(),
          donate_argnums=(), budget: CollectiveBudget | None = None
          ) -> HloAuditReport:
    """AOT-lower ``jax.jit(fn, static_argnums, donate_argnums)`` on
    ``args``, compile it, and audit the artifact. Lowering never executes
    or donates anything — the caller's buffers stay live. With ``budget``
    the report is enforced before being returned.

    The lower+compile runs with SyncTally counting suspended: lowering
    materializes traced constants host-side, which is compile-time work,
    not a serving-path sync."""
    import jax
    from jax.tree_util import keystr, tree_flatten_with_path

    from .tracecheck import sync_tally_paused

    name = name or getattr(fn, "__name__", "jitted")
    static_argnums = tuple(static_argnums)
    donate_argnums = tuple(donate_argnums)
    jit_kwargs = {}
    if static_argnums:
        jit_kwargs["static_argnums"] = static_argnums
    if donate_argnums:
        jit_kwargs["donate_argnums"] = donate_argnums
    with sync_tally_paused(), warnings.catch_warnings():
        # "Some donated buffers were not usable" becomes a structured
        # AliasingViolation below — don't also leak the warning
        warnings.simplefilter("ignore")
        compiled = jax.jit(fn, **jit_kwargs).lower(*args).compile()
        txt = compiled.as_text()
        ma = compiled.memory_analysis()
        ca = compiled.cost_analysis()
    colls, hosts = census(txt)

    # flatten the non-static args the way jit does: flat leaf i <-> compiled
    # parameter i, UNLESS XLA pruned unused parameters (detected below)
    try:
        params = [p.name for p in inspect.signature(fn).parameters.values()]
    except (TypeError, ValueError):
        params = []
    flat: list[str] = []
    donated_idx: set[int] = set()
    donated_bytes = 0
    for i, arg in enumerate(args):
        if i in static_argnums:
            continue
        arg_name = params[i] if i < len(params) else f"arg{i}"
        for path, leaf in tree_flatten_with_path(arg)[0]:
            if i in donate_argnums:
                donated_idx.add(len(flat))
                donated_bytes += _leaf_nbytes(leaf)
            flat.append(arg_name + keystr(path))

    alias_entries = _ALIAS_RE.findall(txt)
    aliased_params = {int(p) for _out, p in alias_entries}
    entry = txt[txt.rfind("\nENTRY"):]
    n_entry_params = len(set(re.findall(r"parameter\((\d+)\)", entry)))
    unaliased: tuple[str, ...] = ()
    if n_entry_params == len(flat):
        # no parameter pruning: compiled param numbers ARE flat leaf indices
        unaliased = tuple(flat[i] for i in sorted(donated_idx)
                          if i not in aliased_params)

    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = float((ca or {}).get("flops", 0.0))
    arg_b = int(ma.argument_size_in_bytes)
    temp_b = int(ma.temp_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    alias_b = int(ma.alias_size_in_bytes)
    report = HloAuditReport(
        name=name, collectives=colls, host_transfers=hosts,
        donated_leaves=len(donated_idx), aliased_leaves=len(alias_entries),
        donated_bytes=donated_bytes, alias_bytes=alias_b,
        unaliased=unaliased, flops=flops, argument_bytes=arg_b,
        temp_bytes=temp_b, output_bytes=out_b,
        # resident set while the step runs (the aot_shard_proof formula:
        # XLA:CPU's peak_memory_in_bytes leaves out the temp arena)
        peak_bytes=arg_b + temp_b + out_b - alias_b)
    if budget is not None:
        report.enforce(budget)
    return report


def audit_guard(guard, args, budget: CollectiveBudget | None = None,
                name: str | None = None) -> HloAuditReport:
    """Audit a CompileGuard-wrapped step: the wrapped impl and its
    static/donate argnums are read off the guard itself, so the audited
    artifact can never desynchronize from what the guard's jit builds."""
    return audit(guard.fn, args, name=name or guard.name,
                 static_argnums=guard.static_argnums,
                 donate_argnums=guard.donate_argnums, budget=budget)


# ----------------------------------------------------------------- registry
@dataclass(frozen=True)
class StepSpec:
    """A named auditable step: ``build()`` returns ``(target, args,
    jit_kwargs, budget)`` where target is a CompileGuard or a plain
    callable (jit_kwargs supplies static/donate argnums for the latter)."""
    name: str
    doc: str
    build: object = field(repr=False)
    min_devices: int = 1


def _build_engine_step(which: str, tensor_parallel: int = 1,
                       kv_dtype: str = "float32",
                       quantized_logits: bool = False):
    """Engine-step audit targets. ``tensor_parallel=2`` builds the SAME
    step on a 2-device mesh (Megatron weight + KV-pool shards via
    serving/tp.py shard_map) with the budget the engine itself declares:
    2 all-reduces per block + 1 for the logits, byte-capped — the
    single-chip variants certify at SINGLE_CHIP (all zeros).
    ``kv_dtype="int8"`` builds the quantized-pool twin: the SAME budgets
    must hold (quantization is per-device arithmetic — zero extra
    collectives), and the donated int8 pools + scale leaves must all
    alias (a donated-but-copied quantized pool would silently forfeit
    the 4x HBM win the mode exists for). ``which="verify_spec"`` builds
    the speculative-decoding verify step (serving/spec.py, n-gram
    proposer at depth 2): the in-jit propose + K+1-token ragged verify +
    accept count as ONE program — zero collectives single-chip, the
    target's own 2L+1 all-reduces (and not one more: the proposer adds
    no collectives) under tensor parallelism, donated pools aliased
    either way."""
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle

    from ..serving.engine import ServingConfig, ServingEngine
    from ..serving.spec import SpecConfig
    from ..text.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(7)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=32, dropout=0.0))
    model.eval()
    spec = (SpecConfig(method="ngram", depth=2)
            if which == "verify_spec" else None)
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, num_pages=16, page_size=4, max_prompt_len=8,
        tensor_parallel=tensor_parallel, kv_dtype=kv_dtype, spec=spec,
        # the tp2 entries certify WITH the overlap contract declared:
        # min_overlap_frac=1.0 over async collectives (vacuous where the
        # backend compiles them sync — the forced CPU mesh — and binding
        # on chip, where the latency-hiding scheduler must deliver)
        tp_overlap_scheduler=tensor_parallel > 1,
        tp_quantized_logits=quantized_logits))
    if which == "verify_spec":
        args = (eng._p, eng.cache.pools,
                jnp.asarray(eng.cache.page_table), jnp.asarray(eng._ctx),
                jnp.asarray(eng._last_tok), jnp.asarray(eng._active),
                jnp.asarray(eng._rids), jnp.asarray(eng._gen),
                jnp.asarray(eng._spec_hist()))
        return eng._verify_jit, args, None, eng._step_budget("verify")
    if which in ("prefill", "prefill_chunk"):
        bucket = eng.prefill_buckets[0]
        padded = np.zeros(bucket, np.int32)
        if which == "prefill":
            padded[:3] = (5, 7, 11)
            tail, ctx0 = 3, 0
        else:
            # chunked prefill: a MID-PROMPT chunk — queries enter at
            # ctx0 > 0 against already-resident KV, through the SAME
            # prefill program shape (chunk padded to its bucket). Audited
            # separately so the registry certifies the exact call
            # signature the chunk phase dispatches, not just the cold
            # ctx0 = 0 case.
            padded[:4] = (3, 5, 7, 11)
            tail, ctx0 = 4, 4
        args = (eng._p, eng.cache.pools, jnp.asarray(padded),
                jnp.asarray(tail, jnp.int32), jnp.asarray(ctx0, jnp.int32),
                jnp.asarray(eng.cache.page_table[0]),
                jnp.asarray(1, jnp.int32))
        return (eng._prefill_jit, args, None,
                eng._step_budget(f"prefill[{bucket}]"))
    args = (eng._p, eng.cache.pools, jnp.asarray(eng.cache.page_table),
            jnp.asarray(eng._ctx), jnp.asarray(eng._last_tok),
            jnp.asarray(eng._active), jnp.asarray(eng._rids),
            jnp.asarray(eng._gen))
    return eng._decode_jit, args, None, eng._step_budget("decode")


def _build_cache_step(which: str, tensor_parallel: int = 1,
                      kv_dtype: str = "float32"):
    """Cache-mover audit targets. ``tensor_parallel=2`` shards the pools'
    heads axis and runs the mover per-shard (shard_map over replicated
    page indices) — pure local data movement, so the declared budget
    stays ZERO collectives either way. ``kv_dtype="int8"`` moves int8
    codes + scale stacks instead of f32 pages (the spill/restore payload
    of the host tier) — still zero collectives, scatter still aliases
    every donated leaf."""
    import jax.numpy as jnp
    import numpy as np

    from ..serving.kv_cache import PagedCacheConfig, PagedKVCache

    tp = None
    if tensor_parallel > 1:
        from ..serving.tp import TPContext
        from ..text.gpt import GPTConfig

        tp = TPContext(tensor_parallel, GPTConfig(
            vocab_size=97, hidden_size=8, num_layers=2, num_heads=2))
    cache = PagedKVCache(PagedCacheConfig(
        num_layers=2, num_heads=2, head_dim=4, num_pages=8, page_size=4,
        max_batch=2, pages_per_seq=4, tp=tp, kv_dtype=kv_dtype))
    cfg = cache.cfg
    idx = jnp.asarray(np.zeros(cfg.pages_per_seq, np.int32))
    if which == "swap_gather":
        return cache._gather_jit, (cache.pools, idx), None, SINGLE_CHIP
    if which == "swap_scatter":
        shape = (cfg.num_layers, cfg.pages_per_seq, cfg.page_size,
                 cfg.num_heads, cfg.head_dim)
        if cfg.quantized:
            sshape = (cfg.num_layers, cfg.pages_per_seq, cfg.num_heads)
            args = (cache.pools, idx, jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape, jnp.int8), jnp.zeros(sshape),
                    jnp.zeros(sshape))
        else:
            args = (cache.pools, idx, jnp.zeros(shape), jnp.zeros(shape))
        return cache._scatter_jit, args, None, SINGLE_CHIP
    args = (cache.pools, jnp.asarray(1, jnp.int32),
            jnp.asarray(2, jnp.int32))
    return cache._copy_jit, args, None, SINGLE_CHIP


_TP8_BATCH, _TP8_HIDDEN, _TP8_FF = 2, 16, 64


def _build_tp8_decode():
    """A toy tensor-parallel decode step: the Megatron split — column-
    parallel first matmul, row-parallel second, ONE psum of the [B, H]
    partials per step. Its declared budget is exactly that all-reduce;
    anything more (an implicit resharding all-gather, say) is a bug."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("tp",))

    def tp_block(x, w1, w2):
        h = jnp.maximum(x @ w1, 0.0)   # [B, FF/8] — column shard, local
        y = h @ w2                     # [B, H] partial sums
        return jax.lax.psum(y, "tp")   # the ONE declared all-reduce

    fn = shard_map(tp_block, mesh=mesh,
                   in_specs=(P(None, None), P(None, "tp"), P("tp", None)),
                   out_specs=P(None, None))
    args = (jnp.ones((_TP8_BATCH, _TP8_HIDDEN), jnp.float32),
            jnp.ones((_TP8_HIDDEN, _TP8_FF), jnp.float32),
            jnp.ones((_TP8_FF, _TP8_HIDDEN), jnp.float32))
    budget = CollectiveBudget(
        all_reduce=1,
        max_collective_bytes=_TP8_BATCH * _TP8_HIDDEN * 4)
    return fn, args, {}, budget


REGISTRY: dict[str, StepSpec] = {s.name: s for s in (
    StepSpec("swap_gather", "paged-cache swap-out gather (read-only, no "
             "donation)", lambda: _build_cache_step("swap_gather")),
    StepSpec("swap_scatter", "paged-cache swap-in scatter (pools donated)",
             lambda: _build_cache_step("swap_scatter")),
    StepSpec("cow_copy", "prefix-cache copy-on-write page copy (pools "
             "donated)", lambda: _build_cache_step("cow_copy")),
    StepSpec("engine_prefill", "serving prefill step, smallest pad bucket "
             "(toy GPT)", lambda: _build_engine_step("prefill")),
    StepSpec("engine_prefill_chunk", "serving CHUNKED prefill step: one "
             "mid-prompt chunk at ctx0 > 0 through the same prefill "
             "program (toy GPT)",
             lambda: _build_engine_step("prefill_chunk")),
    StepSpec("engine_decode", "serving decode step, whole batch (toy GPT)",
             lambda: _build_engine_step("decode")),
    StepSpec("engine_verify_spec", "speculative-decoding verify step: "
             "in-jit n-gram propose + whole-batch K+1-token ragged "
             "verify + accept count, one program (budget: zero "
             "collectives, donated pools aliased)",
             lambda: _build_engine_step("verify_spec")),
    StepSpec("tp8_decode", "toy tensor-parallel shard_map step on an "
             "8-device mesh: budget = exactly one all-reduce",
             _build_tp8_decode, min_devices=8),
    # ---- tensor-parallel serving (ServingConfig(tensor_parallel=2) on a
    # 2-device mesh): the REAL sharded engine steps, certified against the
    # budgets the engine itself declares — 2 all-reduces per block + 1 for
    # the logits, byte-capped (serving/tp.py step_budget); the per-shard
    # cache movers certify at ZERO collectives
    StepSpec("tp2_engine_prefill", "TENSOR-PARALLEL serving prefill step "
             "(tp=2 Megatron shards, budget 2L+1 all-reduces)",
             lambda: _build_engine_step("prefill", tensor_parallel=2),
             min_devices=2),
    StepSpec("tp2_engine_prefill_chunk", "TENSOR-PARALLEL chunked prefill "
             "step: mid-prompt chunk at ctx0 > 0 through the same sharded "
             "program (budget 2L+1 all-reduces)",
             lambda: _build_engine_step("prefill_chunk",
                                        tensor_parallel=2),
             min_devices=2),
    StepSpec("tp2_engine_decode", "TENSOR-PARALLEL serving decode step, "
             "whole batch (budget 2L+1 all-reduces)",
             lambda: _build_engine_step("decode", tensor_parallel=2),
             min_devices=2),
    StepSpec("tp2_engine_verify_spec", "TENSOR-PARALLEL speculative "
             "verify step: the SAME 2L+1 all-reduce budget as decode — "
             "the in-jit proposer adds zero collectives",
             lambda: _build_engine_step("verify_spec", tensor_parallel=2),
             min_devices=2),
    StepSpec("tp2_swap_gather", "per-shard swap-out gather over the "
             "heads-sharded pools (budget: zero collectives)",
             lambda: _build_cache_step("swap_gather", tensor_parallel=2),
             min_devices=2),
    StepSpec("tp2_swap_scatter", "per-shard swap-in scatter (pools "
             "donated; budget: zero collectives)",
             lambda: _build_cache_step("swap_scatter", tensor_parallel=2),
             min_devices=2),
    StepSpec("tp2_cow_copy", "per-shard COW page copy (pools donated; "
             "budget: zero collectives)",
             lambda: _build_cache_step("cow_copy", tensor_parallel=2),
             min_devices=2),
    # ---- quantized paged KV pool (kv_dtype="int8"): int8 codes + per-
    # page-per-head scale leaves, all donated and all aliased; budgets
    # identical to the fp32 twins — quantize/dequantize is per-device
    # arithmetic, so a collective appearing here is a sharding bug
    StepSpec("engine_decode_q8", "serving decode step over the INT8-"
             "quantized pool (codes + scale leaves donated/aliased; "
             "budget: zero collectives)",
             lambda: _build_engine_step("decode", kv_dtype="int8")),
    StepSpec("swap_gather_q8", "swap/spill gather over the int8 pool — "
             "the host-tier spill payload: raw codes + scales, never "
             "dequantized (read-only, no donation)",
             lambda: _build_cache_step("swap_gather", kv_dtype="int8")),
    StepSpec("swap_scatter_q8", "swap/restore scatter into the int8 pool "
             "(codes + scale leaves donated)",
             lambda: _build_cache_step("swap_scatter", kv_dtype="int8")),
    StepSpec("tp2_engine_decode_q8", "TENSOR-PARALLEL decode over the "
             "heads-sharded int8 pool (budget 2L+1 all-reduces — "
             "unchanged by quantization)",
             lambda: _build_engine_step("decode", tensor_parallel=2,
                                        kv_dtype="int8"),
             min_devices=2),
    # ---- quantized logits all-reduce (tp_quantized_logits=True): the
    # b*s*V f32 logits payload ships as int8 codes + a 4-byte shared
    # scale — budget 2L+2 all-reduces with the logits byte term counted
    # at 1 byte/element by the census's bit-accurate dtype table. The
    # byte cap is ~4x tighter than the f32 twin's, so a silently
    # unquantized psum fails loudly here
    StepSpec("tp2_engine_decode_qlogits", "TENSOR-PARALLEL decode with "
             "the EQuARX-style int8 logits all-reduce (budget 2L+2 "
             "all-reduces, logits bytes counted at s8 width + 4-byte "
             "scale)",
             lambda: _build_engine_step("decode", tensor_parallel=2,
                                        quantized_logits=True),
             min_devices=2),
)}


def run_step(name: str) -> HloAuditReport:
    """Build and audit one registered step, enforcing its declared budget.
    Raises HloCheckError on violation (or when the step needs more devices
    than the process has — the CLI respawns onto a forced CPU mesh)."""
    spec = REGISTRY.get(name)
    if spec is None:
        raise KeyError(f"unknown hlocheck step {name!r} "
                       f"(have: {', '.join(REGISTRY)})")
    import jax

    have = len(jax.devices())
    if have < spec.min_devices:
        raise HloCheckError(
            f"step {name!r} needs {spec.min_devices} devices, have {have} "
            f"— run under XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{spec.min_devices} (the --hlo CLI does this automatically)")
    target, args, jit_kwargs, budget = spec.build()
    from .tracecheck import CompileGuard

    if isinstance(target, CompileGuard):
        return audit_guard(target, args, budget=budget, name=name)
    kw = jit_kwargs or {}
    return audit(target, args, name=name, budget=budget, **kw)


# ---------------------------------------------------------------------- CLI
_CHILD_ENV = "PADDLE_TPU_HLOCHECK_CHILD"  # set in respawned children


def _run_in_subprocess(spec: StepSpec,
                       overlap: bool = False,
                       cmd_args: list | None = None,
                       label: str = "hlocheck") -> tuple[int, str]:
    """Re-run one step in a child forced onto a CPU mesh wide enough for
    it (the certification is a virtual-mesh proof, not an on-chip run).
    Returns (exit code, relayed child output) so the caller can classify
    a nonzero exit as budget violation vs execution error. meshcheck
    reuses this respawn mechanism by supplying its own ``cmd_args``
    (the argv after ``-m paddle_tpu.analysis``) and ``label``; only
    ``spec.name`` and ``spec.min_devices`` are read then."""
    import pathlib
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env[_CHILD_ENV] = "1"  # recursion guard: a child never respawns
    # APPEND the forced count (last occurrence wins in XLA) so operator-
    # supplied flags (--xla_dump_to=...) survive into the child
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count="
                        f"{spec.min_devices}").strip()
    root = str(pathlib.Path(__file__).resolve().parents[2])
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    print(f"[{label}] {spec.name}: needs {spec.min_devices} devices — "
          f"re-running on a forced {spec.min_devices}-device CPU mesh")
    if cmd_args is None:
        cmd_args = ["--hlo", "--step", spec.name]
        if overlap:  # the child prints the per-collective view for us
            cmd_args.append("--overlap")
    cmd = [sys.executable, "-m", "paddle_tpu.analysis"] + list(cmd_args)
    try:
        proc = subprocess.run(
            cmd, env=env, timeout=900,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    except subprocess.TimeoutExpired as e:
        # a wedged child must not crash the sweep: report it as an
        # execution error (rc 124, the conventional timeout code) so the
        # remaining steps still run and the summary stays honest
        tail = (e.stdout or b"").decode(errors="replace")[-2000:]
        print(f"[{label}] {spec.name}: child timed out after 900s"
              + (f"\n{tail}" if tail else ""))
        return 124, ""
    out = proc.stdout.decode(errors="replace")
    print(out, end="")
    return proc.returncode, out


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis --hlo",
        description="Compiled-artifact auditor: collective census, "
                    "host-transfer & aliasing verification, HBM/flops "
                    "roll-up for every registered jitted step.")
    parser.add_argument("--step", action="append", default=None,
                        metavar="NAME",
                        help="audit only these registered steps "
                             "(repeatable; default: all)")
    parser.add_argument("--list-steps", action="store_true",
                        help="print the step registry and exit")
    parser.add_argument("--overlap", action="store_true",
                        help="print the per-collective overlap census "
                             "(sync/async form + instructions scheduled "
                             "in flight) for each audited step")
    args = parser.parse_args(argv)

    if args.list_steps:
        for s in REGISTRY.values():
            extra = (f" [needs {s.min_devices} devices]"
                     if s.min_devices > 1 else "")
            print(f"{s.name}  {s.doc}{extra}")
        return 0
    names = args.step or list(REGISTRY)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        print(f"unknown step(s): {', '.join(unknown)} "
              f"(have: {', '.join(REGISTRY)})")
        return 2
    import jax

    violations = errors = 0
    for name in names:
        spec = REGISTRY[name]
        if len(jax.devices()) < spec.min_devices:
            if os.environ.get(_CHILD_ENV):
                # already the respawned child and the forced device count
                # still didn't take: report, never spawn a grandchild
                print(f"FAIL {name}: forced "
                      f"{spec.min_devices}-device CPU mesh did not take "
                      f"effect in the respawned child (execution error, "
                      f"not a budget violation)")
                errors += 1
                continue
            rc, out = _run_in_subprocess(spec, overlap=args.overlap)
            if rc == 0:
                continue
            # a child exits 1 for a real budget violation AND for its own
            # error paths (which self-report "not a budget violation") or
            # an uncaught crash — classify by the child's report, so the
            # summary never sends a reader chasing a nonexistent HLO
            # budget breach
            if rc == 1 and "FAIL" in out \
                    and "not a budget violation" not in out:
                violations += 1
            else:
                print(f"FAIL {name}: respawned child exited rc={rc} "
                      f"(execution error, not a budget violation)")
                errors += 1
            continue
        try:
            report = run_step(name)
            print(report.summary())
            if args.overlap:
                print(report.overlap_summary())
        except HloCheckError as e:
            print(f"FAIL {name}: {e}")
            violations += 1
        except Exception as e:  # noqa: BLE001 — one broken step must not
            # abort the sweep: the remaining steps still run and the
            # summary stays honest, same contract as the child path
            print(f"FAIL {name}: {type(e).__name__}: {e} "
                  f"(execution error, not a budget violation)")
            errors += 1
    if violations or errors:
        print(f"{violations} step(s) over budget, {errors} step(s) "
              f"errored")
    else:
        print(f"hlocheck clean: {len(names)} step(s) within budget")
    return 1 if (violations or errors) else 0
