"""Static certification of Pallas kernels: VMEM budgets, tiling lint,
grid-race detection, and roofline contracts — before hardware ever runs one.

Every Pallas kernel in-tree shipped uncertified: the paged-decode dispatch
in ``kernels/paged_attention.py`` had never run on a chip, silently fell
back on *any* exception, and was skipped entirely for the int8 pools the
production path would actually serve. PRs 6 and 10 set the pattern —
freeze a static budget, audit every compiled artifact once, fail loudly on
drift — and this module extends that certification discipline down to the
kernel level. The unified ragged-attention kernel
(``kernels/ragged_paged_attention.py``, arxiv 2604.15464) landed through
exactly this strip: registered, budgeted, its data-dependent output map
proven injective at runtime ``index_args``, roofline banked.

``certify(fn, args)`` traces a kernel entry point to its jaxpr (under the
same ``i32_index_scope`` its launches use), finds every ``pallas_call``
(recursing through custom_vjp/pjit/scan sub-jaxprs), and checks each
against a frozen :class:`KernelBudget`:

- **VMEM working set** — per grid step, the sum of every VMEM-space
  block's bytes (×2 for grid-varying blocks: Mosaic double-buffers the
  pipeline; ×1 for grid-invariant blocks) plus scratch, against the
  per-generation VMEM cap (:data:`VMEM_CAPS`). ``ANY``/HBM-space operands
  (manually DMA'd pools) and semaphores don't occupy the budget.
- **Tiling lint** — block shapes against the (sublane, lane) minimums per
  dtype ((8,128) f32, (16,128) bf16, (32,128) int8): a lane-misaligned
  block that doesn't cover its array axis is an ERROR (layout-breaking); a
  sub-minimum sublane is a WARNING (Mosaic pads the tile — wasteful, not
  wrong). Array dims must divide by block dims (a partial trailing block
  is silently-unwritten output, the ``fused_layernorm`` rows%8 hazard).
- **Grid-race detection** — each *output* BlockSpec ``index_map`` is
  evaluated over the full grid (bounded by ``budget.max_race_points``)
  and proven injective. Two grid points mapping to the same output block
  along a ``parallel`` dimension is a write race — an error even when
  sequential revisits are declared, unless the budget additionally
  declares ``allow_parallel_revisits`` (the splash scratch-as-output
  idiom: every core writes its own copy, safe only as per-core scratch).
  A revisit along ``arbitrary`` (sequential) dimensions is the legal
  online-accumulation idiom (flash attention revisits its output across
  the KV dim) and passes only when the budget declares
  ``allow_output_revisits``. Index maps reading scalar-prefetch operands
  are data-dependent — injectivity is undecidable statically, so they
  fail closed unless ``allow_data_dependent_outputs`` — AND, when
  ``certify(..., index_args=)`` supplies concrete runtime values for the
  scalar operands (the ragged kernel's ``(ctx_lens, cu_q_lens,
  page_table)``), the map is evaluated for real and the standard
  injectivity proof runs on it: the declaration sanctions the
  data-dependence, the runtime proof resolves it.
- **Roofline contract** — analytical FLOPs (declared per registry entry),
  a static HBM traffic model (block bytes × index-map *transitions* over
  the row-major grid — Mosaic skips the refetch when consecutive steps
  reuse a block), and arithmetic intensity, banked to
  ``profiles/kernelcheck.json`` and diffed against the composite path's
  hlocheck cost roll-up (``hlocheck.audit`` flops + materialized bytes),
  so every kernel carries a predicted-speedup record the future on-chip
  A/B (``tools/flash_autotune.py`` idiom, BENCH_TPU_HISTORY.jsonl) can
  confirm or refute. Re-running against the bank fails loudly on drift
  in any analytic field; the composite-measured side is re-measured and
  reported, never hard-pinned (XLA cost models move across versions).

:data:`REGISTRY` names the in-tree kernel families (flash/splash
attention, the unified ragged paged kernel at its four mode shapes, the
legacy library paged decode, fused layernorm fwd+dx, the fused Adam
update), mirroring ``hlocheck.REGISTRY``; ``run_kernel`` certifies one
entry the way ``hlocheck.run_step`` audits one step.
``coverage_report()`` statically enumerates the dispatch gates
(``FLAGS_use_pallas_kernels``, the unified ``ragged_kernel_eligible``
rules, flash ``flash_route`` incl. the causal pad-to-block rescue) and
reports which serving configs reach a Pallas kernel vs the composite —
PR 11's "int8 decode has no fast kernel" / "head_dim 64 is kernel-less"
findings flipped to covered when the ragged kernel landed, and the
report keeps them that way.

CLI: ``python -m paddle_tpu.analysis kernelcheck [--kernel NAME] [--bank]
[--json PATH]`` (also ``tools/kernelcheck.py``), exit 0 clean / 1 on any
violation / 2 bad usage — everything runs on CPU, no TPU required: only
jaxprs are inspected and only composite references are (AOT-)compiled.

Like hlocheck, this module never imports the kernels at module level —
the registry builders import them lazily, and ``kernels/`` modules import
only :func:`validate_flash_tuned` from here (lazily, at table load).
"""
from __future__ import annotations

import itertools
import json
import math
import os
from dataclasses import dataclass, field

__all__ = ["KernelBudget", "KernelFinding", "PallasCallReport",
           "KernelCertReport", "KernelCheckError", "VMEM_CAPS", "LANE",
           "certify", "KernelSpec", "REGISTRY", "run_kernel",
           "coverage_report", "validate_flash_tuned",
           "validate_ragged_tuned", "bank_path", "diff_banked", "main"]


class KernelCheckError(RuntimeError):
    """A kernel failed static certification."""


# ------------------------------------------------------------------ budgets
#: lane width of every TPU vector tile (minor-most dim), all generations
LANE = 128

#: minimum tile second-to-minor size × dtype width == 32 bytes: (8,128)
#: f32, (16,128) bf16, (32,128) int8/fp8
_SUBLANE_BYTES = 32

#: per-core VMEM by TPU generation (the guide's ~16 MiB/core; kernels are
#: certified against the oldest generation they claim to serve)
VMEM_CAPS = {
    "v3": 16 << 20,
    "v4": 16 << 20,
    "v5e": 16 << 20,
    "v5p": 16 << 20,
}

DEFAULT_GENERATION = "v5e"


@dataclass(frozen=True)
class KernelBudget:
    """Frozen per-kernel certification contract.

    ``vmem_frac`` leaves headroom for Mosaic's internal scratch below the
    hardware cap. ``allow_output_revisits`` sanctions the sequential-
    accumulation idiom (same output block revisited along ``arbitrary``
    grid dims — flash attention's KV loop); a collision along a
    ``parallel`` dim is a race regardless, unless
    ``allow_parallel_revisits`` additionally sanctions it (the splash
    scratch-as-output idiom — statically indistinguishable from a
    megacore write race, so it takes its own explicit declaration and
    still warns). ``allow_data_dependent_outputs`` sanctions output
    index maps that read scalar-prefetch operands (injectivity
    undecidable statically — fail closed by default).
    ``max_race_points`` bounds the grid enumeration of the race proof."""
    generation: str = DEFAULT_GENERATION
    vmem_frac: float = 0.9
    allow_output_revisits: bool = False
    allow_parallel_revisits: bool = False
    allow_data_dependent_outputs: bool = False
    max_race_points: int = 4096

    @property
    def vmem_cap(self) -> int:
        return int(VMEM_CAPS[self.generation] * self.vmem_frac)


# ----------------------------------------------------------------- findings
@dataclass(frozen=True)
class KernelFinding:
    kind: str      # vmem | tiling | race | dispatch | trace | drift
    severity: str  # "error" (fails certification) | "warn" (reported)
    message: str

    def __str__(self) -> str:
        return f"[{self.kind}/{self.severity}] {self.message}"


@dataclass(frozen=True)
class PallasCallReport:
    """Everything one ``pallas_call`` admits statically."""
    name: str
    grid: tuple
    dimension_semantics: tuple
    vmem_bytes: int
    vmem_cap: int
    hbm_bytes: int          # static traffic model (see module docstring)
    block_shapes: tuple     # (operand kind, block dims, array shape, dtype)
    output_revisits: int    # legal sequential revisits observed
    findings: tuple = ()


@dataclass(frozen=True)
class KernelCertReport:
    """One kernel entry point's certificate: every pallas_call it traces
    to, plus the entry-level dispatch-constraint results."""
    name: str
    calls: tuple = ()
    findings: tuple = ()  # entry-level (dispatch constraints, trace)

    def all_findings(self) -> tuple:
        out = list(self.findings)
        for c in self.calls:
            out.extend(c.findings)
        return tuple(out)

    @property
    def errors(self) -> tuple:
        return tuple(f for f in self.all_findings() if f.severity == "error")

    @property
    def ok(self) -> bool:
        return not self.errors

    @property
    def vmem_bytes(self) -> int:
        return max((c.vmem_bytes for c in self.calls), default=0)

    @property
    def hbm_bytes(self) -> int:
        return sum(c.hbm_bytes for c in self.calls)

    def summary(self) -> str:
        grids = ", ".join(str(c.grid) for c in self.calls) or "none"
        state = "OK" if self.ok else \
            f"{len(self.errors)} violation(s)"
        warns = sum(1 for f in self.all_findings() if f.severity == "warn")
        wtxt = f", {warns} warning(s)" if warns else ""
        cap = self.calls[0].vmem_cap if self.calls else 0
        return (f"kernelcheck {self.name}: {len(self.calls)} pallas_call(s);"
                f" grid {grids}; vmem {_fmt_bytes(self.vmem_bytes)} / "
                f"{_fmt_bytes(cap)}; hbm/call {_fmt_bytes(self.hbm_bytes)}; "
                f"{state}{wtxt}")


from .hlocheck import _fmt_bytes  # noqa: E402 — one formatter, two auditors


# ------------------------------------------------------------ jaxpr walking
def _find_pallas_eqns(jaxpr, out=None) -> list:
    """Every ``pallas_call`` eqn in a jaxpr, recursing through sub-jaxprs
    (custom_vjp/pjit/scan/cond params carry Jaxpr/ClosedJaxpr values)."""
    import jax

    out = [] if out is None else out
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            out.append(eqn)
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else [v]
            for x in vals:
                if isinstance(x, jax.core.ClosedJaxpr):
                    _find_pallas_eqns(x.jaxpr, out)
                elif isinstance(x, jax.core.Jaxpr):
                    _find_pallas_eqns(x, out)
    return out


def _memory_space(aval) -> str:
    """Normalized memory-space tag of a block/scratch aval: 'vmem' (the
    default), 'any', 'smem', 'semaphore', ..."""
    ms = getattr(aval, "memory_space", None)
    return "vmem" if ms is None else str(ms).lower()


def _int_block_dims(block_shape) -> list:
    """(axis, size) for the integer dims of a block shape — ``Mapped`` /
    squeezed dims don't exist in the VMEM tile."""
    return [(ax, d) for ax, d in enumerate(block_shape)
            if isinstance(d, int)]


def _block_nbytes(bm) -> int:
    import numpy as np

    n = int(np.dtype(bm.array_shape_dtype.dtype).itemsize)
    for _, d in _int_block_dims(bm.block_shape):
        n *= d
    return n


def _index_map_info(bm, n_grid: int):
    """(data_dependent, constant): does the index map read scalar-prefetch
    operands / is it invariant over the grid (all-literal outputs)?"""
    import jax

    jx = bm.index_map_jaxpr.jaxpr
    used = set()
    for eqn in jx.eqns:
        for v in eqn.invars:
            if isinstance(v, jax.core.Var):
                used.add(v)
    outs = {v for v in jx.outvars if isinstance(v, jax.core.Var)}
    scalar_refs = jx.invars[n_grid:]
    data_dependent = any(v in used or v in outs for v in scalar_refs)
    constant = not any(v in used or v in outs for v in jx.invars[:n_grid])
    return data_dependent, constant


def _eval_index_map(bm, grid, max_points: int, index_args=None):
    """The index map's block-index tuple at each grid point, in row-major
    (pipeline) order. Returns (points, tuples, truncated). Evaluated
    under the i32 scope the map was traced in — the package-global x64
    would promote the literal arithmetic and break mixed-dtype selects.

    ``index_args`` supplies CONCRETE runtime values for the map's
    scalar-prefetch operands (``ctx_lens``/``cu_q_lens``/page tables —
    the ragged kernel's parameterization): with them a data-dependent
    map is evaluated for real and its injectivity PROVEN for that
    representative call instead of failing closed. Scalar-prefetch
    operands appear in the map jaxpr as Refs, so the jaxpr is discharged
    to functional form first (discharge appends the final ref values as
    extra outputs — sliced off)."""
    import jax
    import numpy as np

    from ..kernels._common import i32_index_scope

    jx = bm.index_map_jaxpr
    n_grid = len(grid)
    extras = jx.jaxpr.invars[n_grid:]
    jaxpr, consts = jx.jaxpr, jx.consts
    n_out = len(jaxpr.outvars)
    if extras:
        from jax._src.state.discharge import discharge_state

        jaxpr, consts = discharge_state(jaxpr, consts)
    if index_args is not None:
        vals = [np.asarray(a) for a in index_args]
        if len(vals) != len(extras):
            raise ValueError(
                f"index_args supplies {len(vals)} scalar-prefetch "
                f"value(s) but the index map takes {len(extras)}")
    else:
        # non-data-dependent maps never read these; shape-correct zeros
        # keep the discharged jaxpr evaluable either way
        vals = [np.zeros(tuple(getattr(v.aval, "shape", ()) or ()),
                         getattr(v.aval, "dtype", np.int32))
                for v in extras]
    points, tuples = [], []
    it = itertools.product(*(range(int(g)) for g in grid))
    with i32_index_scope():
        for point in itertools.islice(it, max_points):
            args = [np.int32(i) for i in point] + vals
            out = jax.core.eval_jaxpr(jaxpr, consts, *args)[:n_out]
            points.append(point)
            tuples.append(tuple(int(x) for x in out))
    total = 1
    for g in grid:
        total *= int(g)
    return points, tuples, total > len(points)


# ------------------------------------------------------------- certify core
def _certify_call(eqn, budget: KernelBudget, name: str,
                  index_args=None) -> PallasCallReport:
    import numpy as np

    gm = eqn.params["grid_mapping"]
    grid = tuple(gm.grid)
    cp = eqn.params.get("compiler_params") or {}
    if not isinstance(cp, dict):
        cp = getattr(cp, "__dict__", {}) or {}
    semantics = tuple((cp.get("mosaic") or {}).get("dimension_semantics")
                      or ("arbitrary",) * len(grid))
    findings: list[KernelFinding] = []
    blocks = []

    n_steps = 1
    for g in grid:
        n_steps *= int(g)

    # ---- VMEM + HBM models + tiling lint over the block mappings
    vmem = 0
    hbm = 0
    in_out = ["in"] * gm.num_inputs + ["out"] * gm.num_outputs
    for kind, bm in zip(in_out, gm.block_mappings):
        arr = bm.array_shape_dtype
        dt = np.dtype(arr.dtype)
        space = _memory_space(bm.block_aval)
        nbytes = _block_nbytes(bm)
        blocks.append((kind, tuple(str(d) for d in bm.block_shape),
                       tuple(arr.shape), str(dt)))
        data_dep, constant = _index_map_info(bm, len(grid))

        # tiling lint (VMEM-resident blocks only — ANY-space operands are
        # DMA'd manually and tile at their copy sites)
        if space.startswith("vmem") or space == "vmem":
            ints = _int_block_dims(bm.block_shape)
            for ax, d in ints:
                ad = int(arr.shape[ax])
                if d < ad and ad % d:
                    findings.append(KernelFinding(
                        "tiling", "error",
                        f"{name} {kind} block {bm.block_shape} over array "
                        f"{tuple(arr.shape)}: axis {ax} dim {ad} is not "
                        f"divisible by block dim {d} — the grid truncates "
                        f"and the partial trailing block is silently "
                        f"unwritten/unread"))
            if ints:
                lane_ax, lane_d = ints[-1]
                if lane_d % LANE and lane_d < int(arr.shape[lane_ax]):
                    findings.append(KernelFinding(
                        "tiling", "error",
                        f"{name} {kind} block {bm.block_shape} ({dt}): "
                        f"minor dim {lane_d} is neither a {LANE}-lane "
                        f"multiple nor the whole array axis "
                        f"({arr.shape[lane_ax]}) — Mosaic cannot lay out "
                        f"a strided partial-lane tile"))
            if len(ints) >= 2:
                sub_ax, sub_d = ints[-2]
                min_sub = max(1, _SUBLANE_BYTES // dt.itemsize)
                if sub_d % min_sub and sub_d < int(arr.shape[sub_ax]):
                    findings.append(KernelFinding(
                        "tiling", "warn",
                        f"{name} {kind} block {bm.block_shape} ({dt}): "
                        f"sublane dim {sub_d} is below/off the "
                        f"({min_sub}, {LANE}) minimum tile for {dt} — "
                        f"Mosaic pads the tile (wasteful, not wrong)"))

        # VMEM working set: ×2 for grid-varying blocks (pipeline double
        # buffer), ×1 for invariant blocks; ANY/HBM operands excluded
        if "any" in space or "hbm" in space:
            hbm += int(np.prod(arr.shape)) * dt.itemsize  # manual-DMA bound
            continue
        if "semaphore" in space:
            continue
        vmem += nbytes * (1 if constant else 2)
        # HBM traffic: one fetch per index-map transition in row-major
        # order (consecutive equal indices reuse the resident block)
        if constant:
            hbm += nbytes
        elif data_dep and index_args is None:
            hbm += nbytes * n_steps  # undecidable: every-step upper bound
        else:
            # data-dependent maps WITH runtime index_args evaluate for
            # real — the banked HBM model reflects the canonical call
            # instead of the every-step upper bound
            _, tuples, truncated = _eval_index_map(
                bm, grid, budget.max_race_points, index_args)
            transitions = 1 + sum(1 for a, b in zip(tuples, tuples[1:])
                                  if a != b)
            hbm += nbytes * (n_steps if truncated else transitions)

    # scratch (already sized with its own buffering)
    n_io = gm.num_index_operands + gm.num_inputs + gm.num_outputs
    inner = eqn.params["jaxpr"]
    for var in inner.invars[n_io:]:
        aval = var.aval
        space = _memory_space(aval)
        if "semaphore" in space:
            continue
        shape = getattr(getattr(aval, "inner_aval", aval), "shape", ())
        dtype = getattr(getattr(aval, "inner_aval", aval), "dtype", None)
        try:
            itemsize = np.dtype(dtype).itemsize
        except Exception:  # noqa: BLE001 — exotic ref dtypes don't budget
            continue
        vmem += int(np.prod(shape)) * itemsize if shape else itemsize

    cap = budget.vmem_cap
    if vmem > cap:
        findings.append(KernelFinding(
            "vmem", "error",
            f"{name}: per-grid-step VMEM working set "
            f"{_fmt_bytes(vmem)} exceeds the {budget.generation} budget "
            f"{_fmt_bytes(cap)} ({budget.vmem_frac:.0%} of "
            f"{_fmt_bytes(VMEM_CAPS[budget.generation])}) — shrink the "
            f"block shapes or move operands to ANY/HBM with manual DMA"))

    # ---- grid-race detection over the OUTPUT block mappings
    revisits = 0
    for out_i, bm in enumerate(gm.block_mappings[gm.num_inputs:
                                                 gm.num_inputs
                                                 + gm.num_outputs]):
        data_dep, constant = _index_map_info(bm, len(grid))
        if data_dep and not (index_args is not None
                             and budget.allow_data_dependent_outputs):
            sev = ("warn" if budget.allow_data_dependent_outputs
                   else "error")
            findings.append(KernelFinding(
                "race", sev,
                f"{name} output {out_i}: index_map reads scalar-prefetch "
                f"operands — injectivity over the grid is data-dependent "
                f"and cannot be proven statically"
                + (" (pass index_args= with runtime scalar values to "
                   "prove it for a representative call)" if sev == "warn"
                   else " (declare allow_data_dependent_outputs to "
                        "sanction)")))
            continue
        # a data-dependent output map that reaches here is RESOLVED:
        # allow_data_dependent_outputs is declared AND index_args carry
        # the runtime scalar values, so the map evaluates for real below
        # and the standard run/reappear injectivity proof applies to it
        if len(grid) == 0:
            continue
        points, tuples, truncated = _eval_index_map(
            bm, grid, budget.max_race_points, index_args)
        if truncated:
            findings.append(KernelFinding(
                "race", "warn",
                f"{name} output {out_i}: grid has more than "
                f"{budget.max_race_points} points — race proof covers the "
                f"first {len(points)} (row-major) only"))
        # Mosaic writes an output block back to HBM only when its index
        # CHANGES between consecutive grid steps — a contiguous run of
        # equal indices is the resident-block accumulation idiom (flash's
        # KV loop), legal when the budget declares it. A block index that
        # REAPPEARS after the map moved away is the true overwrite race:
        # the first run's writeback is refetched (or clobbered) by the
        # second. A run whose points differ along a 'parallel' dim spans
        # megacore partitions — a write race (an error even when
        # sequential revisits are declared) unless the budget sanctions
        # it as per-core scratch via allow_parallel_revisits (the splash
        # scratch-as-output idiom), in which case it still warns.
        closed: dict[tuple, tuple] = {}
        run_start = None
        raced = reappeared = par_warned = False
        for point, t in zip(points, tuples):
            if run_start is not None and t == prev_t:
                revisits += 1
                if not par_warned:
                    diff = [ax for ax in range(len(grid))
                            if run_start[ax] != point[ax]]
                    if any(semantics[ax] == "parallel" for ax in diff):
                        par_warned = True
                        par_sev = ("warn" if budget.allow_parallel_revisits
                                   else "error")
                        findings.append(KernelFinding(
                            "race", par_sev,
                            f"{name} output {out_i}: block {t} is "
                            f"revisited across a 'parallel' grid dim "
                            f"({run_start} .. {point}) — a megacore "
                            f"split would write it from both cores; "
                            f"safe only as per-core scratch (the "
                            f"scratch-as-output idiom"
                            + (")" if par_sev == "warn" else
                               " — declare allow_parallel_revisits to "
                               "sanction)")))
                if not budget.allow_output_revisits and not raced:
                    raced = True
                    findings.append(KernelFinding(
                        "race", "error",
                        f"{name} output {out_i}: grid points {run_start} "
                        f"and {point} both map to output block {t} — the "
                        f"in-place accumulation idiom, but this budget "
                        f"does not declare allow_output_revisits, so the "
                        f"kernel overwrites its own output"))
                continue
            if run_start is not None:
                closed[prev_t] = run_start
            if t in closed and not reappeared:
                reappeared = True
                findings.append(KernelFinding(
                    "race", "error",
                    f"{name} output {out_i}: output block {t} written by "
                    f"grid point {point} REAPPEARS after the index map "
                    f"already moved away (first run started at "
                    f"{closed[t]}) — Mosaic wrote the first run back to "
                    f"HBM and this visit clobbers it; two grid indices "
                    f"mapping to the same output block is a write race"))
            run_start, prev_t = point, t

    return PallasCallReport(
        name=name, grid=grid, dimension_semantics=semantics,
        vmem_bytes=int(vmem), vmem_cap=cap, hbm_bytes=int(hbm),
        block_shapes=tuple(blocks), output_revisits=revisits,
        findings=tuple(findings))


def certify(fn, args, *, name: str | None = None,
            budget: KernelBudget | None = None,
            constraints=(), index_args=None) -> KernelCertReport:
    """Trace ``fn(*args)`` to a jaxpr (args may be ShapeDtypeStructs —
    nothing executes, nothing materializes) and certify every
    ``pallas_call`` it contains against ``budget``. ``constraints`` are
    pre-evaluated entry-level dispatch checks ``(name, ok, detail)`` —
    a False one is a dispatch violation (the composite-fallback rules,
    e.g. flash's %block gate, checked statically instead of discovered
    at runtime). ``index_args`` are concrete runtime values for the
    kernel's scalar-prefetch operands (``ctx_lens``/``cu_q_lens``/page
    table): with them, data-dependent output index maps sanctioned by
    ``allow_data_dependent_outputs`` get a REAL injectivity proof for
    the representative call (and data-dependent HBM traffic is counted
    from actual transitions) — resolved, not suppressed."""
    import jax

    from ..kernels._common import i32_index_scope

    name = name or getattr(fn, "__name__", "kernel")
    budget = budget or KernelBudget()
    findings: list[KernelFinding] = []
    for cname, ok, detail in constraints:
        if not ok:
            findings.append(KernelFinding(
                "dispatch", "error",
                f"{name}: dispatch constraint {cname!r} does not hold for "
                f"the certified shapes — {detail}"))
    try:
        with i32_index_scope():  # kernels trace like their launches
            jaxpr = jax.make_jaxpr(lambda *a: fn(*a))(*args)
    except Exception as e:  # noqa: BLE001 — an untraceable kernel is the
        # finding (the paged-decode x64 bug shipped exactly this way)
        findings.append(KernelFinding(
            "trace", "error",
            f"{name}: kernel entry point failed to trace "
            f"({type(e).__name__}: {str(e)[:300]}) — every launch would "
            f"silently take the composite fallback"))
        return KernelCertReport(name=name, findings=tuple(findings))
    eqns = _find_pallas_eqns(jaxpr.jaxpr)
    if not eqns:
        findings.append(KernelFinding(
            "trace", "error",
            f"{name}: no pallas_call reached from the entry point — the "
            f"certified function dispatches to a composite path"))
    calls = tuple(
        _certify_call(eqn, budget,
                      name if len(eqns) == 1 else f"{name}[{i}]",
                      index_args=index_args)
        for i, eqn in enumerate(eqns))
    return KernelCertReport(name=name, calls=calls,
                            findings=tuple(findings))


# --------------------------------------------------------- flash_tuned lint
def validate_flash_tuned(table: dict) -> list[str]:
    """Tiling-constraint validation for ``kernels/flash_tuned.json``
    entries (``"seq,head_dim" -> block edge``), shared by the load site in
    ``kernels/flash_attention.py`` and the writer in
    ``tools/flash_autotune.py``: a misaligned entry is rejected with a
    clear error at load/bank time, never discovered as a runtime Pallas
    failure. Returns error strings (empty = clean)."""
    errors = []
    for key, blk in sorted(table.items()):
        try:
            s, d = (int(x) for x in str(key).split(","))
        except ValueError:
            errors.append(f"{key!r}: key must be 'seq,head_dim' ints")
            continue
        if not isinstance(blk, int) or blk <= 0:
            errors.append(f"{key!r}: block edge {blk!r} must be a "
                          f"positive int")
            continue
        if blk % LANE:
            errors.append(f"{key!r}: block edge {blk} is not a multiple "
                          f"of the {LANE}-lane MXU tile")
        if blk > s:
            errors.append(f"{key!r}: block edge {blk} exceeds seq {s}")
        elif s % blk:
            errors.append(f"{key!r}: block edge {blk} does not tile "
                          f"seq {s} (s % block != 0 dies inside Pallas)")
        if d % 64:
            errors.append(f"{key!r}: head_dim {d} is not a multiple of "
                          f"the 64-lane tile the kernel requires")
    return errors


def validate_ragged_tuned(table: dict) -> list[str]:
    """Constraint validation for ``kernels/ragged_tuned.json`` entries,
    shared by the load site in ``kernels/ragged_paged_attention.py`` and
    the writer in ``tools/ragged_autotune.py`` — the flash_tuned
    discipline: load can never see an entry bank rejected. A value under
    a ``"page_size,num_heads,head_dim"`` key is either the legacy bare
    ``block_heads`` int or the pipeline-aware dict schema
    ``{"block_heads": B, "pipeline_chunk": C, "pages_per_seq": P}``:
    ``B`` must divide ``num_heads`` and ``C`` must divide the ``P``
    recorded at tune time — a STALE entry whose chunk no longer divides
    its page count is rejected here, not discovered as a mis-tiled
    launch. Returns error strings (empty = clean)."""
    errors = []
    for key, val in sorted(table.items()):
        try:
            ps, h, d = (int(x) for x in str(key).split(","))
        except ValueError:
            errors.append(f"{key!r}: key must be "
                          f"'page_size,num_heads,head_dim' ints")
            continue
        if ps <= 0 or h <= 0 or d <= 0:
            errors.append(f"{key!r}: page_size/num_heads/head_dim must "
                          f"be positive")
            continue
        if isinstance(val, dict):
            unknown = set(val) - {"block_heads", "pipeline_chunk",
                                  "pages_per_seq"}
            if unknown:
                errors.append(f"{key!r}: unknown field(s) "
                              f"{sorted(unknown)} — the dict schema is "
                              f"block_heads/pipeline_chunk/pages_per_seq")
                continue
            bh = val.get("block_heads", 1)
            chunk = val.get("pipeline_chunk")
            pages = val.get("pages_per_seq")
        else:
            bh, chunk, pages = val, None, None
        if not isinstance(bh, int) or bh <= 0:
            errors.append(f"{key!r}: block_heads {bh!r} must be a "
                          f"positive int")
            continue
        if h % bh:
            errors.append(f"{key!r}: block_heads {bh} does not divide "
                          f"num_heads {h} — the head grid dim would "
                          f"truncate and the tail heads would be "
                          f"silently unserved")
        if chunk is None:
            continue
        if not isinstance(chunk, int) or chunk <= 0:
            errors.append(f"{key!r}: pipeline_chunk {chunk!r} must be a "
                          f"positive int")
            continue
        if not isinstance(pages, int) or pages <= 0:
            errors.append(f"{key!r}: pipeline_chunk {chunk} without a "
                          f"positive pages_per_seq — the chunk is only "
                          f"meaningful against the page count it was "
                          f"tuned at")
            continue
        if pages % chunk:
            errors.append(f"{key!r}: pipeline_chunk {chunk} does not "
                          f"divide pages_per_seq {pages} — a stale "
                          f"entry (the page count moved since the "
                          f"tune); re-run tools/ragged_autotune.py")
    return errors


# ----------------------------------------------------------------- registry
@dataclass(frozen=True)
class KernelSpec:
    """A named certifiable kernel: ``build()`` returns a dict with the
    entry point, example args (ShapeDtypeStructs — trace-only), budget,
    dispatch constraints, analytic FLOPs, and the composite reference the
    roofline is diffed against through ``hlocheck.audit``."""
    name: str
    doc: str
    build: object = field(repr=False)


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _build_flash():
    import jax.numpy as jnp

    from ..kernels import flash_attention as fa
    from ..kernels.attention import sdpa_reference

    b, h, s, d = 1, 2, 1024, 128
    q = _sds((b, h, s, d), jnp.float32)
    blk = fa._block(s, d)
    constraints = (
        ("supports_shape", fa.supports_shape((b, h, s, d), (b, h, s, d)),
         f"seq {s} must tile the tuned block edge {blk} and the 128 MXU "
         f"tile, head_dim {d} the 64-lane tile"),
        ("composite_fallback_640",
         not fa.supports_shape((b, h, 640, d), (b, h, 640, d)),
         "s=640 passes %128 but not %512 — it must take the composite "
         "path, not die inside Pallas (the flash_attention.py "
         "supports_shape rule, now certified statically)"),
    )
    return dict(
        fn=lambda q, k, v: fa._flash(q, k, v, True, 0.125),
        args=(q, q, q),
        # the KV grid dim revisits the output block — the online-softmax
        # accumulation contract
        budget=KernelBudget(allow_output_revisits=True),
        constraints=constraints,
        # causal MACs ~ half the dense s_q x s_k square, x2 matmuls (qk,
        # av), x2 flops/MAC
        flops=float(2 * b * h * s * s * d),
        composite=lambda q, k, v: sdpa_reference(q, k, v, is_causal=True,
                                                 scale=0.125),
        composite_args=(q, q, q))


def _build_splash():
    import jax.numpy as jnp

    from ..kernels import flash_attention as fa
    from ..kernels.attention import sdpa_reference

    b, h, s, d = 1, 2, 1024, 128
    q = _sds((b, h, s, d), jnp.float32)
    return dict(
        fn=lambda q, k, v: fa._splash_impl(q, k, v, 0.125, False),
        args=(q, q, q),
        # the library splash kernel emits its logsumexp/max stats as
        # outputs revisited across the parallel head dim — per-core
        # scratch-as-output, sanctioned explicitly (and still warned)
        budget=KernelBudget(allow_output_revisits=True,
                            allow_parallel_revisits=True),
        constraints=(
            ("block_tiles_seq", s % fa._block(s, d) == 0,
             "splash block edges must tile the sequence"),),
        flops=float(2 * b * h * s * s * d),
        composite=lambda q, k, v: sdpa_reference(q, k, v, is_causal=True,
                                                 scale=0.125),
        composite_args=(q, q, q))


# the canonical serving decode shape the coverage report and the paged
# certificate share: bench-model head_dim on the 128-lane tile, 16-token
# pages, 32 pages per sequence (512-token context window)
_PAGED_SHAPE = dict(batch=2, heads=2, head_dim=128, num_pages=64,
                    page_size=16, pages_per_seq=32)


def _build_paged_decode():
    import jax.numpy as jnp

    from ..kernels import paged_attention as pa
    from ..kernels.attention import sdpa_reference

    p = _PAGED_SHAPE
    b, h, d = p["batch"], p["heads"], p["head_dim"]
    ps, pps = p["page_size"], p["pages_per_seq"]
    S = ps * pps
    q = _sds((b, h, 1, d), jnp.float32)
    pool = _sds((p["num_pages"], ps, h, d), jnp.float32)
    table = _sds((b, pps), jnp.int32)
    ctx = _sds((b,), jnp.int32)
    ok, _why = pa.decode_kernel_eligible(d, pps, ps, num_heads=h)
    ok_q8, why_q8 = pa.decode_kernel_eligible(d, pps, ps, num_heads=h,
                                              quantized=True)
    constraints = (
        ("decode_kernel_eligible", ok,
         "the serving decode shape must pass every dispatch gate"),
        # the PR 11 'int8_skip_is_declared' constraint, inverted: the
        # unified ragged kernel fuses the dequant, so the quantized
        # serving path is now kernel-ELIGIBLE — certified here so the
        # coverage flip can never silently regress
        ("int8_served_by_unified_kernel", ok_q8, why_q8),
    )

    def composite(q, kp, vp, table, ctx):
        k_all = pa.paged_gather(kp, table)
        v_all = pa.paged_gather(vp, table)
        mask = pa.ragged_mask(ctx, k_all.shape[2], 1)
        return sdpa_reference(q, k_all, v_all, mask=mask)

    return dict(
        fn=lambda q, kp, vp, t, c: pa._pallas_decode(q, kp, vp, t, c, None),
        args=(q, pool, pool, table, ctx),
        budget=KernelBudget(),
        constraints=constraints,
        flops=float(4 * b * h * S * d),
        composite=composite,
        composite_args=(q, pool, pool, table, ctx))


def _build_ragged(mode: str):
    """The unified ragged paged-attention kernel at one serving mode's
    canonical shape: ``decode`` (s=1 fp32), ``q8`` (s=1, int8 codes +
    per-page-per-head scales, dequant fused into the gather), ``verify``
    (the spec K+1=5 contract), ``prefill`` (single-row chunk tail, 64-pad
    bucket at ctx0=192). All four trace to the SAME program shape — one
    kernel, four certificates — and all four certify the PIPELINED form
    (``pipeline_chunk=8`` over the 32-page canonical row: 4 chunks
    through 2 alternating staging buffers), so the scratch the VMEM
    model prices carries the ×2 double-buffer cost explicitly in its
    leading axis. ``index_args`` carry the canonical runtime
    scalar-prefetch values (ctx_lens, cu_q_lens, page table) so the
    data-dependent output index map is PROVEN injective, and the HBM
    model counts the canonical call's actual block transitions."""
    import numpy as np

    import jax.numpy as jnp

    from ..kernels import paged_attention as pa
    from ..kernels import ragged_paged_attention as rp
    from ..kernels.attention import sdpa_reference

    p = _PAGED_SHAPE
    b, h, d = p["batch"], p["heads"], p["head_dim"]
    ps, pps, npages = p["page_size"], p["pages_per_seq"], p["num_pages"]
    s = {"decode": 1, "q8": 1, "verify": 5, "prefill": 64}[mode]
    if mode == "prefill":
        b = 1
    quant = mode == "q8"
    S = ps * pps
    q = _sds((b, h, s, d), jnp.float32)
    pool = _sds((npages, ps, h, d), jnp.int8 if quant else jnp.float32)
    table = _sds((b, pps), jnp.int32)
    ctx = _sds((b,), jnp.int32)
    # canonical runtime values: a non-trivial page permutation and ragged
    # mid-context lengths — what the injectivity proof and the banked HBM
    # transition counts are evaluated at
    tab_np = (np.arange(1, 1 + b * pps, dtype=np.int32)
              .reshape(b, pps) % npages)
    ctx_np = (np.asarray([192], np.int32) if mode == "prefill"
              else np.asarray([317, 129][:b], np.int32))
    cu_np = np.arange(b + 1, dtype=np.int32) * s
    chunk = 8  # 4 chunks over the canonical 32-page row: pipeline ON
    ok, why = rp.ragged_kernel_eligible(d, pps, ps, s, num_heads=h,
                                        quantized=quant,
                                        pipeline_chunk=chunk)
    ok64, why64 = rp.ragged_kernel_eligible(64, pps, ps, s, num_heads=h,
                                            quantized=quant)
    constraints = (
        ("ragged_kernel_eligible", ok, why or
         "the canonical shape must pass every unified-kernel gate "
         "(incl. the x2 staged buffers at the certified chunk)"),
        # the two kernelcheck coverage gaps this kernel exists to close,
        # certified so they can never silently reopen
        ("head_dim_64_eligible", ok64, why64 or
         "head_dim 64 must stay covered by the unified kernel"),
    )

    if quant:
        scale = _sds((npages, h), jnp.float32)

        def fn(q, kp, vp, t, c, ksc, vsc):
            return rp.ragged_paged_attention(q, kp, vp, t, c,
                                             k_scale=ksc, v_scale=vsc,
                                             pipeline_chunk=chunk)

        def composite(q, kp, vp, t, c, ksc, vsc):
            k_all = pa.paged_gather_quant(kp, ksc, t, q.dtype)
            v_all = pa.paged_gather_quant(vp, vsc, t, q.dtype)
            mask = pa.ragged_mask(c, k_all.shape[2], s)
            return sdpa_reference(q, k_all, v_all, mask=mask)

        args = (q, pool, pool, table, ctx, scale, scale)
    else:
        def fn(q, kp, vp, t, c):
            return rp.ragged_paged_attention(q, kp, vp, t, c,
                                             pipeline_chunk=chunk)

        def composite(q, kp, vp, t, c):
            k_all = pa.paged_gather(kp, t)
            v_all = pa.paged_gather(vp, t)
            mask = pa.ragged_mask(c, k_all.shape[2], s)
            return sdpa_reference(q, k_all, v_all, mask=mask)

        args = (q, pool, pool, table, ctx)

    return dict(
        fn=fn, args=args,
        # the data-dependent output map (cu_q_lens[b] // s) is sanctioned
        # AND resolved: index_args below give the proof its runtime values
        budget=KernelBudget(allow_data_dependent_outputs=True),
        constraints=constraints,
        index_args=(ctx_np, cu_np, tab_np),
        # qk + av MACs over the gathered width, x2 flops/MAC
        flops=float(4 * b * h * s * S * d),
        composite=composite, composite_args=args)


def _build_ln(which: str):
    import jax.numpy as jnp

    from ..kernels import fused_layernorm as fl

    rows, d = 256, 512
    x = _sds((rows, d), jnp.float32)
    vec = _sds((d,), jnp.float32)
    stat = _sds((rows, 1), jnp.float32)
    constraints = (
        ("rows_divisible", rows % fl._ROW_BLOCK == 0,
         f"rows % {fl._ROW_BLOCK} != 0 truncates the grid — the partial "
         f"trailing block would be silently UNWRITTEN output"),
        ("lane_tileable", d % fl._LANE == 0,
         "the norm dim must tile the 128-lane VPU row"),
        ("dispatch_min_rows", rows >= fl._MIN_ROWS,
         "below _MIN_ROWS the launch overhead loses to XLA fusion"),
    )

    def composite_fwd(x, g, b):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean((x - mu) * (x - mu), axis=-1, keepdims=True)
        rstd = 1.0 / jnp.sqrt(var + 1e-5)
        return (x - mu) * rstd * g + b, mu, rstd

    if which == "fwd":
        return dict(
            fn=lambda x, g, b: fl._call_fwd(x, g, b, 1e-5, False),
            args=(x, vec, vec), budget=KernelBudget(),
            constraints=constraints,
            flops=float(8 * rows * d),  # mean + centered var + normalize
            composite=composite_fwd, composite_args=(x, vec, vec))

    def composite_dx(x, g, mu, rstd, dy):
        xhat = (x - mu) * rstd
        wdy = dy * g
        c1 = jnp.mean(wdy, axis=-1, keepdims=True)
        c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
        return rstd * (wdy - c1 - xhat * c2)

    return dict(
        fn=lambda x, g, mu, rstd, dy: fl._call_dx(x, g, mu, rstd, dy,
                                                  False),
        args=(x, vec, stat, stat, x), budget=KernelBudget(),
        constraints=constraints,
        flops=float(11 * rows * d),
        composite=composite_dx, composite_args=(x, vec, stat, stat, x))


def _build_adam():
    import jax.numpy as jnp

    from ..kernels import fused_optimizer as fo

    n = 1 << 16
    buf = _sds((n,), jnp.float32)
    sc = _sds((), jnp.float32)
    tile = fo._LANE * 8 * fo._ROWS_PER_BLOCK
    constraints = (
        ("size_tileable", n % tile == 0,
         f"size % {tile} != 0 would force a pad-copy of all four inputs — "
         f"the exact HBM traffic the kernel exists to avoid"),
        ("dispatch_min_size", n >= fo._MIN_FUSED_SIZE,
         "small params are free under XLA fusion"),
    )

    def composite(p, g, m, v, lr, bc1, bc2):
        beta1, beta2, eps = 0.9, 0.999, 1e-8
        m2 = beta1 * m + (1.0 - beta1) * g
        v2 = beta2 * v + (1.0 - beta2) * (g * g)
        p2 = p - lr * (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
        return p2, m2, v2

    return dict(
        fn=lambda p, g, m, v, lr, bc1, bc2: fo.fused_adam_update(
            p, g, m, v, lr, bc1, bc2, beta1=0.9, beta2=0.999, eps=1e-8),
        args=(buf, buf, buf, buf, sc, sc, sc),
        budget=KernelBudget(), constraints=constraints,
        flops=float(14 * n),  # m(3) + v(4) + update(6) + apply(1) per elem
        composite=composite,
        composite_args=(buf, buf, buf, buf, sc, sc, sc))


REGISTRY: dict[str, KernelSpec] = {s.name: s for s in (
    KernelSpec("flash_fwd", "dense-block flash attention forward (causal, "
               "seq 1024, head_dim 128) — output revisited across the KV "
               "grid dim by declaration", _build_flash),
    KernelSpec("splash_fwd", "causal splash attention forward (tile-"
               "skipping mask, seq 1024) — same accumulation contract",
               _build_splash),
    KernelSpec("paged_decode", "LEGACY library paged-decode kernel at "
               "the canonical serving shape — kept certified as the "
               "pre-unification A/B baseline; dispatch routes through "
               "ragged_paged instead", _build_paged_decode),
    KernelSpec("ragged_paged", "UNIFIED ragged paged attention, decode "
               "mode (s=1, fp32) — one Pallas program for all four "
               "serving attention modes; data-dependent output map "
               "proven injective at runtime index_args",
               lambda: _build_ragged("decode")),
    KernelSpec("ragged_paged_q8", "unified ragged kernel, int8 mode: "
               "per-page-per-head dequant fused into the page gather — "
               "the quantized serving path's first kernel (closes the "
               "int8-decode coverage gap)",
               lambda: _build_ragged("q8")),
    KernelSpec("ragged_paged_verify", "unified ragged kernel at the "
               "speculative K+1=5 verify contract — the per-depth "
               "verify programs collapse onto the one program shape",
               lambda: _build_ragged("verify")),
    KernelSpec("ragged_paged_prefill", "unified ragged kernel at the "
               "single-row chunked-prefill tail (64-pad bucket, "
               "ctx0=192) — prefill and chunk ride the same program",
               lambda: _build_ragged("prefill")),
    KernelSpec("fused_layernorm_fwd", "fused LayerNorm forward (one HBM "
               "pass per row block, stats saved for the backward)",
               lambda: _build_ln("fwd")),
    KernelSpec("fused_layernorm_dx", "fused LayerNorm dx backward (row-"
               "local second kernel)", lambda: _build_ln("dx")),
    KernelSpec("fused_adam", "fused Adam/AdamW update (one read + one "
               "write per buffer — the bandwidth floor)", _build_adam),
)}


def run_kernel(name: str) -> tuple[KernelCertReport, dict]:
    """Build and certify one registered kernel; returns (report, record)
    where record is the bankable roofline entry — analytic FLOPs, the
    static HBM model, arithmetic intensity, and the composite path's
    hlocheck cost roll-up with the predicted bandwidth-bound speedup."""
    spec = REGISTRY.get(name)
    if spec is None:
        raise KeyError(f"unknown kernelcheck kernel {name!r} "
                       f"(have: {', '.join(REGISTRY)})")
    b = spec.build()
    report = certify(b["fn"], b["args"], name=name, budget=b["budget"],
                     constraints=b.get("constraints", ()),
                     index_args=b.get("index_args"))
    hbm = report.hbm_bytes
    flops = b["flops"]
    record = {
        "grid": [list(c.grid) for c in report.calls],
        "vmem_bytes": report.vmem_bytes,
        "flops": flops,
        "hbm_bytes": hbm,
        "intensity": round(flops / hbm, 3) if hbm else None,
    }
    if b.get("composite") is not None:
        from .hlocheck import audit

        comp = audit(b["composite"], b["composite_args"],
                     name=f"{name}_composite")
        # the composite's materialized traffic: arguments + every
        # intermediate the fused kernel keeps on-chip + outputs
        comp_bytes = (comp.argument_bytes + comp.temp_bytes
                      + comp.output_bytes)
        record["composite"] = {
            "flops": comp.flops,
            "materialized_bytes": comp_bytes,
            "peak_bytes": comp.peak_bytes,
        }
        record["predicted_speedup"] = (
            round(comp_bytes / hbm, 3) if hbm else None)
    return report, record


# --------------------------------------------------------- banking + drift
#: analytic record fields frozen by the bank — drift here is a violation
#: (the PR 6 fail-loudly contract); composite-measured fields re-measure
ANALYTIC_KEYS = ("grid", "vmem_bytes", "flops", "hbm_bytes")


def bank_path() -> str:
    """profiles/kernelcheck.json beside the repo root — the one TRACKED
    file under the otherwise-gitignored profiles/ (it is the frozen
    contract every sweep diffs against, so it must survive a fresh
    checkout)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "profiles", "kernelcheck.json")


def diff_banked(records: dict, banked: dict) -> list[KernelFinding]:
    """Drift check of fresh records against the banked roofline: any
    analytic field that moved is an error naming the field and both
    values; composite re-measurements drift only as warnings (XLA cost
    models move across versions); a kernel missing from the bank asks for
    a --bank run."""
    findings = []
    for name, rec in sorted(records.items()):
        old = banked.get(name)
        if old is None:
            findings.append(KernelFinding(
                "drift", "error",
                f"{name}: no banked roofline entry — run `python -m "
                f"paddle_tpu.analysis kernelcheck --bank` to freeze it"))
            continue
        for key in ANALYTIC_KEYS:
            if old.get(key) != rec.get(key):
                findings.append(KernelFinding(
                    "drift", "error",
                    f"{name}: analytic roofline field {key!r} drifted "
                    f"from the banked contract: {old.get(key)!r} -> "
                    f"{rec.get(key)!r} — re-bank deliberately or fix the "
                    f"kernel"))
        oc, nc = old.get("composite"), rec.get("composite")
        if oc and nc:
            for key in ("flops", "materialized_bytes"):
                a, bb = oc.get(key) or 0, nc.get(key) or 0
                if a and bb and not math.isclose(a, bb, rel_tol=0.25):
                    findings.append(KernelFinding(
                        "drift", "warn",
                        f"{name}: composite {key} moved {a:.4g} -> "
                        f"{bb:.4g} (re-measured, not pinned)"))
    return findings


# ----------------------------------------------------- dispatch coverage
def coverage_report() -> dict:
    """Statically enumerate the kernel-dispatch gates and report which
    serving configs reach a Pallas kernel vs the composite path.

    Rows come from the SAME predicates the runtime dispatch calls
    (``paged_attention.decode_kernel_eligible``,
    ``flash_attention.supports_shape``), so the table cannot drift from
    the dispatch. ``kernel_less`` lists the production-relevant configs
    (TPU backend, kernels flag on) that still take the composite — the
    machine-readable version of "int8 decode has no fast kernel"."""
    from ..kernels import flash_attention as fa
    from ..kernels import paged_attention as pa

    p = _PAGED_SHAPE
    rows = []
    for platform in ("tpu", "cpu"):
        for flags_on in (True, False):
            for kv in ("float32", "int8"):
                ok, why = pa.decode_kernel_eligible(
                    p["head_dim"], p["pages_per_seq"], p["page_size"],
                    num_heads=p["heads"], quantized=kv == "int8",
                    on_tpu=platform == "tpu", flags_on=flags_on)
                rows.append({
                    "family": "paged_decode",
                    "config": (f"platform={platform} "
                               f"pallas_flag={'on' if flags_on else 'off'}"
                               f" kv_dtype={kv}"),
                    "path": "pallas" if ok else "composite",
                    "blocked_by": why})
    ok, why = pa.decode_kernel_eligible(64, p["pages_per_seq"],
                                        p["page_size"],
                                        num_heads=p["heads"])
    rows.append({"family": "paged_decode",
                 "config": ("platform=tpu pallas_flag=on kv_dtype=float32 "
                            "head_dim=64"),
                 "path": "pallas" if ok else "composite",
                 "blocked_by": why})
    # the unified kernel's multi-token modes: chunked-prefill tail (the
    # pad bucket) and the speculative K+1 verify, both dtypes — the SAME
    # decode_kernel_eligible predicate at num_query_tokens > 1, so these
    # rows track the dispatch for free
    for mode, nq in (("verify[K+1=5]", 5), ("prefill[64]", 64)):
        for kv in ("float32", "int8"):
            ok, why = pa.decode_kernel_eligible(
                p["head_dim"], p["pages_per_seq"], p["page_size"],
                num_heads=p["heads"], quantized=kv == "int8",
                num_query_tokens=nq)
            rows.append({
                "family": "ragged_paged",
                "config": (f"platform=tpu pallas_flag=on kv_dtype={kv} "
                           f"mode={mode}"),
                "path": "pallas" if ok else "composite",
                "blocked_by": why})
    for s in (1024, 640, 512):
        shape = (1, 8, s, 128)
        route = fa.flash_route(shape, shape, causal=True)
        path = {"direct": "pallas", "pad": "pallas[padded]"}.get(
            route, "composite")
        rows.append({
            "family": "flash_prefill",
            "config": f"platform=tpu pallas_flag=on seq={s} causal",
            "path": path,
            "blocked_by": "" if route else (
                f"seq {s} fails supports_shape (%128 MXU tile and "
                f"%{fa._block(s, 128)} block edge) and the causal "
                f"pad-to-block route")})
    # the %512 edge WITHOUT the causal pad rescue: non-causal can't pad
    # (padded keys would be attended) — a loudly-counted fallback
    # (serving_flash_edge_fallback_total), never a silent one
    shape = (1, 8, 640, 128)
    route = fa.flash_route(shape, shape, causal=False)
    rows.append({
        "family": "flash_prefill",
        "config": "platform=tpu pallas_flag=on seq=640 non-causal",
        "path": "pallas" if route else "composite[counted]",
        "blocked_by": "" if route else (
            "non-causal seq 640 cannot pad-to-block; composite serves "
            "and serving_flash_edge_fallback_total counts it")})
    for gate, why in (("pallas_flag=off", "FLAGS_use_pallas_kernels off"),
                      ("platform=cpu", "CPU backend: Pallas TPU kernels "
                                       "unavailable")):
        rows.append({"family": "flash_prefill",
                     "config": f"{gate} seq=1024",
                     "path": "composite", "blocked_by": why})
    kernel_less = [
        f"{r['family']}: {r['config']} — {r['blocked_by']}"
        for r in rows
        if r["path"] == "composite"
        and "platform=tpu" in r["config"]
        and "pallas_flag=off" not in r["config"]]
    return {"rows": rows, "kernel_less": kernel_less}


# ---------------------------------------------------------------------- CLI
def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis kernelcheck",
        description="Static Pallas-kernel certification: VMEM budgets, "
                    "tiling lint, grid-race proofs, roofline contracts, "
                    "and the dispatch-coverage report — all on CPU.")
    parser.add_argument("--kernel", action="append", default=None,
                        metavar="NAME",
                        help="certify only these registered kernels "
                             "(repeatable; default: all)")
    parser.add_argument("--list-kernels", action="store_true",
                        help="print the kernel registry and exit")
    parser.add_argument("--bank", action="store_true",
                        help="write the roofline records to the profile "
                             "instead of diffing against it")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="also dump the full report (certs, "
                             "rooflines, coverage) as JSON")
    parser.add_argument("--profile", default=None, metavar="PATH",
                        help=f"banked-roofline path (default: "
                             f"{bank_path()})")
    parser.add_argument("--no-coverage", action="store_true",
                        help="skip the dispatch-coverage report")
    args = parser.parse_args(argv)

    if args.list_kernels:
        for s in REGISTRY.values():
            print(f"{s.name}  {s.doc}")
        return 0
    names = args.kernel or list(REGISTRY)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        print(f"unknown kernel(s): {', '.join(unknown)} "
              f"(have: {', '.join(REGISTRY)})")
        return 2

    violations = 0
    records: dict[str, dict] = {}
    reports: dict[str, KernelCertReport] = {}
    failures: dict[str, str] = {}
    for name in names:
        try:
            report, record = run_kernel(name)
        except Exception as e:  # noqa: BLE001 — one broken entry must not
            # abort the sweep (the hlocheck CLI contract)
            failures[name] = f"{type(e).__name__}: {e} (execution error)"
            print(f"FAIL {name}: {failures[name]}")
            violations += 1
            continue
        reports[name] = report
        records[name] = record
        print(report.summary())
        for f in report.all_findings():
            print(f"  {f}")
        if not report.ok:
            violations += 1

    profile = args.profile or bank_path()
    drift: list[KernelFinding] = []
    if args.bank:
        if violations:
            print("not banking: certification violations above")
        else:
            os.makedirs(os.path.dirname(profile), exist_ok=True)
            merged = dict(records)
            if set(names) != set(REGISTRY) and os.path.exists(profile):
                # partial --kernel bank: merge into the existing bank —
                # overwriting it would destroy the OTHER kernels' frozen
                # contracts. A full sweep rewrites (drops stale entries).
                with open(profile) as fh:
                    merged = {**json.load(fh), **records}
            with open(profile, "w") as fh:
                json.dump(merged, fh, indent=1, sort_keys=True)
            print(f"banked {len(records)} roofline record(s) to {profile}")
    elif os.path.exists(profile):
        # diff_banked walks `records`, so a --kernel subset diffs exactly
        # the selected entries — drift is never silently unchecked
        with open(profile) as fh:
            drift = diff_banked(records, json.load(fh))
        for f in drift:
            print(f"  {f}")
        violations += sum(1 for f in drift if f.severity == "error")
    else:
        print(f"no banked roofline at {profile} — run --bank to freeze "
              f"the contracts")

    cov = None
    if not args.no_coverage:
        cov = coverage_report()
        print("\ndispatch coverage (gates evaluated statically):")
        for r in cov["rows"]:
            blocked = f"  [{r['blocked_by']}]" if r["blocked_by"] else ""
            print(f"  {r['family']:14s} {r['config']:58s} "
                  f"-> {r['path']}{blocked}")
        if cov["kernel_less"]:
            print("kernel-less production configs "
                  "(TPU + kernels flag on, still composite):")
            for k in cov["kernel_less"]:
                print(f"  !! {k}")

    # roofline table (the README's per-kernel view)
    if records:
        print("\nroofline contracts (analytic, banked):")
        print(f"  {'kernel':22s} {'flops':>12s} {'hbm bytes':>12s} "
              f"{'intensity':>9s} {'vs composite':>12s}")
        for name, rec in records.items():
            sp = rec.get("predicted_speedup")
            print(f"  {name:22s} {rec['flops']:12.4g} "
                  f"{rec['hbm_bytes']:12d} "
                  f"{rec['intensity'] or 0:9.2f} "
                  f"{('%.2fx' % sp) if sp else '-':>12s}")

    if args.json:
        payload = {
            "kernels": {**{n: {
                "ok": reports[n].ok,
                "findings": [str(f) for f in reports[n].all_findings()],
                **records.get(n, {})} for n in reports},
                # a kernel whose run_kernel() raised must not vanish from
                # the machine-readable report while stdout says FAIL
                **{n: {"ok": False, "findings": [msg]}
                   for n, msg in failures.items()}},
            "coverage": cov,
            "drift": [str(f) for f in drift],
        }
        with open(args.json, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)

    if violations:
        print(f"\n{violations} kernel(s)/check(s) in violation")
    else:
        print(f"\nkernelcheck clean: {len(reports)} kernel(s) certified")
    return 1 if violations else 0
