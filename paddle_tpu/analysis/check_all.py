"""One-shot static-analysis gate: every engine, one exit code.

``python -m paddle_tpu.analysis all`` (or ``tools/check_all.py``) runs
the four analysis engines back to back, IN PROCESS, and folds their
exit codes into the shared contract (0 clean / 1 findings / 2 usage):

1. the lint default sweep (rules PT001-PT016 over the package +
   ``tests/`` + ``examples/``),
2. the hlocheck step registry (collective census, aliasing, byte caps),
3. the kernelcheck kernel registry (VMEM/tiling/race/roofline bank),
4. the meshcheck entry registry (per-medium placement + link-time bank).

Every engine runs even when an earlier one fails — a gate that stops at
the first finding hides the rest of the report — and the summary names
each engine's verdict. Narrowing flags (``--hlo-step`` / ``--kernel`` /
``--mesh-step``, each repeatable; ``--skip ENGINE``) keep the in-process
tier-1 pin of the clean run cheap without forking four interpreters.
"""
from __future__ import annotations

__all__ = ["ENGINES", "main"]

#: engine name -> (module attr producing main(argv), description)
ENGINES = ("lint", "hlocheck", "kernelcheck", "meshcheck")


def _engine_main(name: str):
    if name == "lint":
        from .lint import main
    elif name == "hlocheck":
        from .hlocheck import main
    elif name == "kernelcheck":
        from .kernelcheck import main
    else:
        from .meshcheck import main
    return main


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis all",
        description="One-shot static-analysis gate: lint sweep + "
                    "hlocheck registry + kernelcheck registry + "
                    "meshcheck registry, unified exit codes (0 clean, "
                    "1 findings, 2 usage).")
    parser.add_argument("--skip", action="append", default=[],
                        choices=list(ENGINES), metavar="ENGINE",
                        help="skip one engine (repeatable)")
    parser.add_argument("--hlo-step", action="append", default=None,
                        metavar="NAME",
                        help="narrow hlocheck to these steps (repeatable)")
    parser.add_argument("--kernel", action="append", default=None,
                        metavar="NAME",
                        help="narrow kernelcheck to these kernels "
                             "(repeatable)")
    parser.add_argument("--mesh-step", action="append", default=None,
                        metavar="NAME",
                        help="narrow meshcheck to these entries "
                             "(repeatable)")
    args = parser.parse_args(argv)

    engine_argv = {
        "lint": [],
        "hlocheck": [a for n in (args.hlo_step or [])
                     for a in ("--step", n)],
        "kernelcheck": [a for n in (args.kernel or [])
                        for a in ("--kernel", n)],
        "meshcheck": [a for n in (args.mesh_step or [])
                      for a in ("--step", n)],
    }
    results: dict[str, int] = {}
    for name in ENGINES:
        if name in args.skip:
            continue
        print(f"==== {name} ".ljust(60, "="))
        try:
            rc = _engine_main(name)(engine_argv[name])
        except SystemExit as e:  # argparse errors inside an engine
            rc = int(e.code or 0)
        except Exception as e:  # noqa: BLE001 — one broken engine must
            # not mask the others' reports; it still fails the gate
            print(f"FAIL {name}: {type(e).__name__}: {e}")
            rc = 1
        results[name] = rc

    print("==== gate ".ljust(60, "="))
    for name, rc in results.items():
        verdict = ("clean" if rc == 0 else
                   "FINDINGS" if rc == 1 else f"USAGE ERROR (rc={rc})")
        print(f"{name:<12} {verdict}")
    if not results:
        print("nothing ran (everything skipped)")
        return 2
    if any(rc == 2 for rc in results.values()):
        return 2
    return 1 if any(results.values()) else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
