"""paddle_tpu.analysis — trace-time jit auditor, compiled-artifact
auditor, and AST repo linter.

Turns the serving stack's hand-pinned invariants into enforced checks:

- :mod:`~paddle_tpu.analysis.tracecheck` — :class:`CompileGuard` (trace
  counting + compile budgets + retrace *explanation* + donation checks),
  :func:`donation_audit` (jaxpr-level donated-but-unused detection), and
  :class:`SyncTally` (host-sync counting so a decode loop can be certified
  sync-free). The serving engine's ``compile_counts`` surface is built on
  CompileGuard; ``ServingConfig(debug_checks=True)`` turns the audits on
  at every step boundary.
- :mod:`~paddle_tpu.analysis.hlocheck` — the compiled-artifact twin: AOT-
  lower any step and audit the optimized HLO — collective census against
  a declared :class:`~paddle_tpu.analysis.hlocheck.CollectiveBudget`,
  host-transfer ops, XLA input-output aliasing honoring every donation,
  and flops/peak-HBM roll-up. ``python -m paddle_tpu.analysis --hlo``
  sweeps the registered steps (including the 8-device ``shard_map``
  tensor-parallel certification the sharded-serving arc gates on).
- :mod:`~paddle_tpu.analysis.kernelcheck` — static certification of the
  Pallas kernels themselves: trace each registered kernel entry point to
  its jaxpr and certify every ``pallas_call`` against a frozen
  :class:`~paddle_tpu.analysis.kernelcheck.KernelBudget` — VMEM working
  set per grid step, (sublane, lane) tiling lint, output index-map
  injectivity over the grid (write races proven absent before hardware
  ever runs), and a roofline contract banked to
  ``profiles/kernelcheck.json`` and diffed against the composite path's
  hlocheck cost roll-up. ``python -m paddle_tpu.analysis kernelcheck``
  sweeps the registry + the dispatch-coverage report (which serving
  configs reach a Pallas kernel vs the composite).
- :mod:`~paddle_tpu.analysis.meshcheck` — the topology-aware complement
  to hlocheck's topology-blind census: attribute every collective's
  ``replica_groups`` to a declared :class:`MeshTopology` axis, classify
  each axis ICI vs DCN via the cluster model's ``axis_medium``, enforce
  :class:`CollectiveBudget`'s per-medium arms (``max_ici_bytes`` /
  ``max_dcn_bytes`` / ``max_dcn_ops``), and bank the link-time model to
  ``profiles/meshcheck.json``. ``python -m paddle_tpu.analysis
  meshcheck`` sweeps the entry registry (the tp2 engine steps on a
  1-host topology with a BINDING zero-DCN budget, plus the 2-host x
  1-chip entry whose tp axis provably crosses the host boundary).
- :mod:`~paddle_tpu.analysis.lint` — rules PT001-PT016 distilled from bugs
  this repo shipped, with ``# lint: disable=PTxxx`` pragmas and allowlists.
  ``python -m paddle_tpu.analysis paddle_tpu/`` must stay clean (a tier-1
  test enforces zero findings).
- :mod:`~paddle_tpu.analysis.check_all` — the one-shot gate: all four
  engines back to back, in process, one exit code
  (``python -m paddle_tpu.analysis all`` / ``tools/check_all.py``).
"""
from .hlocheck import (SINGLE_CHIP, AliasingViolation,  # noqa: F401
                       CollectiveBudget, CollectiveBudgetError,
                       HloAuditReport, HloCheckError, HostTransferError)
from .kernelcheck import (KernelBudget, KernelCertReport,  # noqa: F401
                          KernelCheckError, KernelFinding,
                          validate_flash_tuned)
from .kernelcheck import certify as certify_kernel  # noqa: F401
from .lint import (ALLOWLIST, RULES, Finding, lint_paths,  # noqa: F401
                   lint_source)
from .meshcheck import (MeshCheckError, MeshReport,  # noqa: F401
                        MeshTopology, multi_host_topology,
                        single_host_topology)
from .meshcheck import analyze as analyze_mesh  # noqa: F401
from .tracecheck import (CompileGuard, DonationViolation,  # noqa: F401
                         RetraceError, SyncTally, SyncViolation,
                         abstract_signature, donation_audit,
                         explain_signature_diff, sync_tally_paused)

__all__ = ["CompileGuard", "RetraceError", "DonationViolation",
           "SyncViolation", "SyncTally", "donation_audit",
           "abstract_signature", "explain_signature_diff",
           "sync_tally_paused",
           "CollectiveBudget", "HloAuditReport", "HloCheckError",
           "CollectiveBudgetError", "HostTransferError",
           "AliasingViolation", "SINGLE_CHIP",
           "KernelBudget", "KernelCertReport", "KernelCheckError",
           "KernelFinding", "certify_kernel", "validate_flash_tuned",
           "MeshTopology", "MeshReport", "MeshCheckError",
           "single_host_topology", "multi_host_topology", "analyze_mesh",
           "Finding", "RULES", "ALLOWLIST", "lint_source", "lint_paths"]
