"""paddle_tpu.analysis — trace-time jit auditor + AST repo linter.

Turns the serving stack's hand-pinned invariants into enforced checks:

- :mod:`~paddle_tpu.analysis.tracecheck` — :class:`CompileGuard` (trace
  counting + compile budgets + retrace *explanation* + donation checks),
  :func:`donation_audit` (jaxpr-level donated-but-unused detection), and
  :class:`SyncTally` (host-sync counting so a decode loop can be certified
  sync-free). The serving engine's ``compile_counts`` surface is built on
  CompileGuard; ``ServingConfig(debug_checks=True)`` turns the audits on
  at every step boundary.
- :mod:`~paddle_tpu.analysis.lint` — rules PT001-PT007 distilled from bugs
  this repo shipped, with ``# lint: disable=PTxxx`` pragmas and allowlists.
  ``python -m paddle_tpu.analysis paddle_tpu/`` must stay clean (a tier-1
  test enforces zero findings).
"""
from .lint import (ALLOWLIST, RULES, Finding, lint_paths,  # noqa: F401
                   lint_source)
from .tracecheck import (CompileGuard, DonationViolation,  # noqa: F401
                         RetraceError, SyncTally, SyncViolation,
                         abstract_signature, donation_audit,
                         explain_signature_diff)

__all__ = ["CompileGuard", "RetraceError", "DonationViolation",
           "SyncViolation", "SyncTally", "donation_audit",
           "abstract_signature", "explain_signature_diff",
           "Finding", "RULES", "ALLOWLIST", "lint_source", "lint_paths"]
