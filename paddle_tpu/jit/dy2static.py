"""dy2static: AST conversion of python control flow over Tensors.

Reference analog: the dygraph_to_static stack
(/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:860 ProgramTranslator + ifelse_transformer.py,
loop_transformer.py) — rewrite `if`/`while` whose predicates depend on
Tensors into functional control-flow ops, so the traced program stays valid
when values are symbolic.

TPU-native lowering:
- tensor-predicate `if`: both branches evaluate, results merge per-leaf with
  `where(pred, t, f)` — under jit XLA emits selects (branches are pure; this
  is the `cond` pattern XLA itself uses for small branches).
- tensor-predicate `while`: a real `lax.while_loop` over the loop-carried
  variables (reverse-mode AD through it is not supported by XLA — same as
  training through an unbounded loop anywhere).
- python predicates keep python semantics untouched.

Subset contract (checked where possible, documented otherwise): branches must
be side-effect-free; a variable consumed after a tensor-`if` must be assigned
in both branches or exist beforehand; loop-carried values must keep shape and
dtype.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["convert_control_flow", "run_if", "run_while", "MISSING"]


class _Missing:
    def __repr__(self):
        return "<dy2static: variable not assigned on the taken branch>"


MISSING = _Missing()


def _is_symbolic(x):
    v = x._value if isinstance(x, Tensor) else x
    return isinstance(v, (jax.Array, jax.core.Tracer)) or hasattr(v, "dtype")


def _pred_value(pred):
    v = pred._value if isinstance(pred, Tensor) else pred
    return v


# ------------------------------------------------------------- runtime helpers
def run_if(pred, true_fn, false_fn, env):
    """Transformed `if` lands here. Python predicate -> one branch runs;
    symbolic predicate -> both run, leaves merge with where(pred, ...)."""
    p = _pred_value(pred)
    if not _is_symbolic(p):
        return true_fn(dict(env)) if p else false_fn(dict(env))
    out_t = true_fn(dict(env))
    out_f = false_fn(dict(env))
    merged = {}
    for k in out_t:
        a, b = out_t[k], out_f.get(k, MISSING)
        if a is MISSING and b is MISSING:
            merged[k] = MISSING
            continue
        if a is MISSING or b is MISSING:
            raise NameError(
                f"dy2static: variable {k!r} is assigned in only one branch of "
                "a tensor-dependent `if`; assign it in both branches (or "
                "before the if)")
        av = a._value if isinstance(a, Tensor) else a
        bv = b._value if isinstance(b, Tensor) else b
        if _is_symbolic(av) or _is_symbolic(bv):
            sel = jnp.where(p, av, bv)
            merged[k] = Tensor(sel) if isinstance(a, Tensor) or \
                isinstance(b, Tensor) else sel
        elif av is bv or av == bv:
            merged[k] = a
        elif isinstance(av, (bool, int, float)) and \
                isinstance(bv, (bool, int, float)):
            # python scalars diverging across a tensor `if` promote to a 0-d
            # tensor select — the reference converts such variables to
            # tensors the same way (break/continue flags rely on this)
            merged[k] = jnp.where(p, av, bv)
        else:
            raise ValueError(
                f"dy2static: non-tensor variable {k!r} takes different "
                f"values ({av!r} vs {bv!r}) across a tensor-dependent "
                "`if` — that value cannot be selected at runtime")
    return merged


def run_while(cond_fn, body_fn, env):
    """Transformed `while` lands here. Symbolic predicate -> lax.while_loop
    over the carried env (Tensors are pytree leaves); python predicate ->
    plain loop. A predicate that BECOMES symbolic mid-loop (a tensor
    break/continue flag set on iteration 1) switches to lax.while_loop with
    the current env as the carry."""
    env = dict(env)
    p = cond_fn(dict(env))
    while not _is_symbolic(_pred_value(p)):
        if not _pred_value(p):
            return env
        env = body_fn(dict(env))
        p = cond_fn(dict(env))
    # only pre-initialized vars are loop-carried; body-local temps (MISSING at
    # entry) recompute each iteration and stay unbound after the loop — a
    # functional while cannot carry a variable with no initial value
    keys = sorted(k for k, v in env.items() if v is not MISSING)

    def c(vals):
        pv = _pred_value(cond_fn(dict(zip(keys, vals))))
        return jnp.asarray(pv).reshape(())

    def b(vals):
        out = body_fn(dict(zip(keys, vals)))
        return tuple(out[k] for k in keys)

    vals = jax.lax.while_loop(c, b, tuple(env[k] for k in keys))
    out = dict(env)  # MISSING entries survive so the guarded rebind skips them
    out.update(zip(keys, vals))
    return out


def _snapshot(frame_locals, keys):
    return {k: frame_locals.get(k, MISSING) for k in keys}


# --------------------------------------------------------------- AST transform
class _AssignedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)  # the def binds its name; don't descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    # synthesized helper/out names from earlier (nested) transforms are
    # implementation detail, never loop-carried user state
    return {n for n in v.names if not n.startswith("__jst_")}


class _ReadNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _reads(node_or_stmts):
    v = _ReadNames()
    for s in (node_or_stmts if isinstance(node_or_stmts, list)
              else [node_or_stmts]):
        v.visit(s)
    return {n for n in v.names if not n.startswith("__jst")}


def _load_prologue(keys):
    """Guarded `k = __jst_env['k']`: a key that is absent/MISSING stays
    unbound so reads fall through to globals/builtins (e.g. `jnp` in a loop
    condition)."""
    out = []
    for k in sorted(keys):
        out.append(ast.parse(
            f"if not __jst.missing(__jst_env, {k!r}):\n"
            f"    {k} = __jst_env[{k!r}]").body[0])
    return out


def _return_epilogue(keys):
    # snapshot() maps still-unassigned names to MISSING instead of NameError
    return ast.parse(f"return __jst.snapshot(locals(), {sorted(keys)!r})").body[0]


def _rebind(keys, out_name):
    """Guarded rebind: a MISSING result leaves the name unbound, preserving
    python's UnboundLocalError instead of leaking the sentinel downstream."""
    return [ast.parse(
        f"if not __jst.missing({out_name}, {k!r}):\n"
        f"    {k} = {out_name}[{k!r}]").body[0] for k in sorted(keys)]


def _has_flow_escape(stmts):
    """True if return/break/continue appears at THIS function's level —
    nested function bodies (incl. the __jst_* helpers synthesized by earlier
    transforms) have their own flow and must not mask conversion."""

    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, node):
            self.found = True

        def visit_Break(self, node):
            self.found = True

        def visit_Continue(self, node):
            self.found = True

        def visit_FunctionDef(self, node):
            pass  # don't descend

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _contains_break_continue(stmts):
    """Break/Continue belonging to THIS loop level: descend into If bodies
    but not into nested loops or function definitions."""
    for s in stmts:
        if isinstance(s, (ast.Break, ast.Continue)):
            return True
        if isinstance(s, ast.If):
            if _contains_break_continue(s.body) or \
                    _contains_break_continue(s.orelse):
                return True
        elif isinstance(s, (ast.With,)):
            if _contains_break_continue(s.body):
                return True
    return False


class _BreakContinueTransformer(ast.NodeTransformer):
    """Rewrite loops containing break/continue into flag-guarded form
    (reference: dygraph_to_static/break_continue_transformer.py):

        while test:                 __brk = False
            ...                     while __jst.loop_cond(test, __brk):
            if p: break       =>        __cont = False
            rest                        ...
                                        if p: __brk = True; __cont = True
                                        if __jst.not_(__cont): rest

    A python predicate keeps the flags python bools (plain loop, original
    semantics); a tensor predicate turns them into bool tensors that the
    main transformer's run_if/run_while carry functionally."""

    def __init__(self):
        self.n = 0
        self._top = None

    def visit_FunctionDef(self, node):
        if self._top is None:
            self._top = node
            self.generic_visit(node)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    def _rewrite_body(self, stmts, brk, cont, allow_break=True):
        out = []
        for i, st in enumerate(stmts):
            if isinstance(st, ast.Break) and allow_break:
                out += ast.parse(f"{brk} = True\n{cont} = True").body
                break  # anything after an unconditional break is dead
            if isinstance(st, ast.Continue):
                out.append(ast.parse(f"{cont} = True").body[0])
                break
            carries_flow = isinstance(st, (ast.If, ast.With)) and (
                _contains_break_continue(getattr(st, "body", []))
                or _contains_break_continue(getattr(st, "orelse", [])))
            if carries_flow:
                if isinstance(st, ast.If):
                    new_st = ast.If(
                        test=st.test,
                        body=self._rewrite_body(st.body, brk, cont)
                        or [ast.Pass()],
                        orelse=self._rewrite_body(st.orelse, brk, cont),
                    )
                else:  # With wrapping a break/continue (no_grad, auto_cast…)
                    new_st = ast.With(
                        items=st.items,
                        body=self._rewrite_body(st.body, brk, cont)
                        or [ast.Pass()],
                    )
                out.append(new_st)
                rest = self._rewrite_body(stmts[i + 1:], brk, cont)
                if rest:
                    guard = ast.parse(f"if __jst.not_({cont}):\n    pass"
                                      ).body[0]
                    guard.body = rest
                    out.append(guard)
                return out
            out.append(st)
        return out

    def _flagged_while(self, test_expr, body, brk, cont):
        shell = ast.parse(
            f"{brk} = False\n"
            f"while __jst.loop_cond(__TEST__, {brk}):\n"
            f"    {cont} = False").body
        loop = shell[1]
        loop.test.args[0] = test_expr
        loop.body = loop.body + self._rewrite_body(body, brk, cont)
        return shell

    def visit_While(self, node):
        self.generic_visit(node)  # inner loops first (their own flags)
        if node.orelse or not _contains_break_continue(node.body):
            return node
        self.n += 1
        brk, cont = f"__bc_brk_{self.n}", f"__bc_cont_{self.n}"
        return self._flagged_while(node.test, node.body, brk, cont)

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse or not _contains_break_continue(node.body):
            return node
        # same range() subset as visit_For below; others stay python
        it = node.iter
        if (not isinstance(node.target, ast.Name)
                or not isinstance(it, ast.Call)
                or not isinstance(it.func, ast.Name) or it.func.id != "range"
                or it.keywords or not 1 <= len(it.args) <= 3):
            return node
        step_val = 1
        if len(it.args) == 3:
            s = it.args[2]
            if not (isinstance(s, ast.Constant) and isinstance(s.value, int)
                    and s.value != 0):
                return node
            step_val = s.value
        if len(it.args) == 1:
            start, stop = ast.Constant(value=0), it.args[0]
        else:
            start, stop = it.args[0], it.args[1]
        self.n += 1
        brk, cont = f"__bc_brk_{self.n}", f"__bc_cont_{self.n}"
        cn, sn = f"__bc_i_{self.n}", f"__bc_stop_{self.n}"
        tgt = node.target.id
        pre = ast.parse(f"{cn} = __START__\n{sn} = __STOP__").body
        pre[0].value = start
        pre[1].value = stop
        cmp_op = "<" if step_val > 0 else ">"
        test = ast.parse(f"{cn} {cmp_op} {sn}", mode="eval").body
        # counter increments BEFORE the guarded body so continue can't skip it
        body = ast.parse(f"{tgt} = {cn}\n{cn} = {cn} + ({step_val})").body \
            + list(node.body)
        return pre + self._flagged_while(test, body, brk, cont)


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self._top = None

    def visit_FunctionDef(self, node):
        # transform the function being converted; don't descend into nested
        # function definitions (their control flow is theirs)
        if self._top is None:
            self._top = node
            self.generic_visit(node)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    def _fresh(self, base):
        self.counter += 1
        return f"__jst_{base}_{self.counter}"

    def visit_If(self, node):
        node = self.generic_visit(node)  # transform nested ifs first
        keys = _assigned(node.body) | _assigned(node.orelse)
        if not keys:
            return node  # pure side-effect if (prints etc.): leave it
        if _has_flow_escape(node.body + node.orelse):
            # return/break/continue in a branch: leave the python `if` as-is
            # (correct for python predicates; a tensor predicate will surface
            # jax's tracer-bool error — reference return_transformer territory)
            return node
        tname, fname, oname = (self._fresh("true"), self._fresh("false"),
                               self._fresh("out"))

        def branch(name, body):
            fn = ast.parse(f"def {name}(__jst_env):\n    pass").body[0]
            fn.body = (_load_prologue(keys) + (body or [ast.Pass()])
                       + [_return_epilogue(keys)])
            return fn

        call = ast.parse(
            f"{oname} = __jst.run_if(__jst_PRED__, {tname}, {fname}, "
            f"__jst.snapshot(locals(), {sorted(keys)!r}))").body[0]
        call.value.args[0] = node.test  # splice the original predicate expr
        return ([branch(tname, node.body), branch(fname, node.orelse), call]
                + _rebind(keys, oname))

    def visit_For(self, node):
        """`for i in range(...)` desugars to the while machinery (reference
        loop_transformer.py for_loop handling). Subset: simple Name target,
        range() with 1-3 args (a step must be a literal int so its sign is
        static), no else/break/continue. Anything else stays python.

        The loop target is a body-local of the while: after a zero-iteration
        python range it stays unbound (python semantics); after a
        tensor-bound loop it is not readable (functional loops don't leak
        body temps — documented subset edge)."""
        it = node.iter
        if (node.orelse or _has_flow_escape(node.body)
                or not isinstance(node.target, ast.Name)
                or not isinstance(it, ast.Call)
                or not isinstance(it.func, ast.Name) or it.func.id != "range"
                or it.keywords or not 1 <= len(it.args) <= 3):
            return self.generic_visit(node)
        step_val = 1
        if len(it.args) == 3:
            s = it.args[2]
            if not (isinstance(s, ast.Constant) and isinstance(s.value, int)
                    and s.value != 0):
                return self.generic_visit(node)  # dynamic step sign: python
            step_val = s.value
        if len(it.args) == 1:
            start, stop = ast.Constant(value=0), it.args[0]
        else:
            start, stop = it.args[0], it.args[1]
        tgt = node.target.id
        self.counter += 1
        cn, sn = f"__d2s_c_{self.counter}", f"__d2s_stop_{self.counter}"
        cmp_op = "<" if step_val > 0 else ">"
        # range args hoisted to names: evaluated exactly once, like range()
        pre = ast.parse(f"{cn} = __START__\n{sn} = __STOP__").body
        pre[0].value = start
        pre[1].value = stop
        shell = ast.parse(
            f"while {cn} {cmp_op} {sn}:\n"
            f"    {tgt} = {cn}\n"
            f"    {cn} = {cn} + ({step_val})").body[0]
        # original (unvisited) body spliced in; visit_While transforms it once
        shell.body = shell.body[:1] + list(node.body) + shell.body[1:]
        converted = self.visit_While(shell)
        return pre + (converted if isinstance(converted, list) else [converted])

    def visit_While(self, node):
        node = self.generic_visit(node)
        if node.orelse:
            return node  # while/else: out of subset, leave untouched
        keys = _assigned(node.body) | (_reads(node.test) - {"__jst"})
        if not keys:
            return node
        if _has_flow_escape(node.body):
            return node  # python while stays; see visit_If note
        cname, bname, oname = (self._fresh("cond"), self._fresh("body"),
                               self._fresh("out"))
        cond_fn = ast.parse(f"def {cname}(__jst_env):\n    pass").body[0]
        cond_fn.body = _load_prologue(keys) + [
            ast.fix_missing_locations(ast.Return(value=node.test))]
        body_fn = ast.parse(f"def {bname}(__jst_env):\n    pass").body[0]
        body_fn.body = (_load_prologue(keys) + node.body
                        + [_return_epilogue(keys)])
        call = ast.parse(
            f"{oname} = __jst.run_while({cname}, {bname}, "
            f"__jst.snapshot(locals(), {sorted(keys)!r}))").body[0]
        return [cond_fn, body_fn, call] + _rebind(keys, oname)


class _JstNamespace:
    run_if = staticmethod(run_if)
    run_while = staticmethod(run_while)
    snapshot = staticmethod(_snapshot)
    MISSING = MISSING

    @staticmethod
    def missing(env, key):
        return key not in env or env[key] is MISSING

    @staticmethod
    def loop_cond(test, brk):
        """`test and not brk`, tensor-aware (break/continue flag loops)."""
        tv = test._value if isinstance(test, Tensor) else test
        bv = brk._value if isinstance(brk, Tensor) else brk
        if _is_symbolic(tv) or _is_symbolic(bv):
            return Tensor(jnp.logical_and(
                jnp.asarray(tv).reshape(()),
                jnp.logical_not(jnp.asarray(bv).reshape(()))))
        return bool(tv) and not bool(bv)

    @staticmethod
    def not_(x):
        xv = x._value if isinstance(x, Tensor) else x
        if _is_symbolic(xv):
            return Tensor(jnp.logical_not(xv))
        return not xv


def convert_control_flow(fn):
    """AST-convert `fn` so tensor-dependent if/while survive tracing
    (the ProgramTranslator entry point; compose with paddle.jit.to_static)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn  # no source (builtins, lambdas from REPL): nothing to do
    tree = ast.parse(src)
    fdef = tree.body[0]
    # drop decorators so applying @to_static(...) around this doesn't recurse
    fdef.decorator_list = []
    _BreakContinueTransformer().visit(fdef)
    ast.fix_missing_locations(tree)
    _ControlFlowTransformer().visit(fdef)
    ast.fix_missing_locations(tree)
    code = compile(tree, filename=f"<dy2static {fn.__name__}>", mode="exec")
    glb = dict(fn.__globals__)
    glb["__jst"] = _JstNamespace
    # exec can't recreate closures: splice the current cell values of the
    # original function's free variables in as globals
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            glb[name] = cell.cell_contents
    loc: dict = {}
    exec(code, glb, loc)  # noqa: S102 — compiling the user's own source
    out = loc[fdef.name]
    out = functools.wraps(fn)(out)
    out.__wrapped_original__ = fn
    return out
