"""paddle.jit — trace-to-XLA compilation.

Reference analog: dy2static (`python/paddle/fluid/dygraph/dygraph_to_static/`,
ProgramTranslator → run_program op). TPU-native: no AST rewriting — `to_static`
traces the layer/function ONCE with jax, caches the compiled XLA executable per
input signature, and runs it with buffer donation. This is the IPU whole-graph
compile model (§3.5 of the survey) applied to dygraph.
"""
from __future__ import annotations

import functools
import os

import jax
import numpy as np

from ..core import rng as rng_mod
from ..core import tape as tape_mod
from ..core.tensor import Tensor


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


def _sig_of(args):
    sig = []
    for a in args:
        if isinstance(a, Tensor):
            sig.append(("T", tuple(a.shape), str(a._value.dtype)))
        elif isinstance(a, np.ndarray):
            sig.append(("A", a.shape, str(a.dtype)))
        else:
            sig.append(("S", a))
    return tuple(sig)


class TracedLayer:
    """Wraps a Layer or function into a jit-compiled callable with param capture."""

    def __init__(self, fn_or_layer, input_spec=None, donate_buffers=False):
        self._target = fn_or_layer
        self._input_spec = input_spec
        self._cache = {}
        self._is_layer = hasattr(fn_or_layer, "named_parameters")

    def __call__(self, *args, **kwargs):
        key = _sig_of(args)
        if key not in self._cache:
            self._cache[key] = self._build(args, kwargs)
        runner = self._cache[key]
        return runner(*args, **kwargs)

    def _build(self, args, kwargs):
        target = self._target
        if self._is_layer:
            params, buffers = target.functional_state()
            p_arrays = {k: v._value for k, v in params.items()}
            b_arrays = {k: v._value for k, v in buffers.items()}

            @functools.partial(jax.jit)
            def compiled(p, b, key, *xs):
                with tape_mod.no_grad(), rng_mod.trace_rng_scope(key):
                    out, new_b = target.functional_call(
                        {k: v for k, v in p.items()}, {k: v for k, v in b.items()}, *xs
                    )
                flat = jax.tree_util.tree_map(
                    lambda t: t._value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor),
                )
                return flat, new_b

            def runner(*xs, **kw):
                arrs = [x._value if isinstance(x, Tensor) else x for x in xs]
                cur_p = {k: v._value for k, v in target.functional_state()[0].items()}
                cur_b = {k: v._value for k, v in target.functional_state()[1].items()}
                key = rng_mod.next_rng_key()
                out, new_b = compiled(cur_p, cur_b, key, *arrs)
                # write back updated buffers (BN running stats)
                _, bufs = target.functional_state()
                for k, v in new_b.items():
                    if k in bufs and bufs[k] is not None:
                        bufs[k]._value = v
                return jax.tree_util.tree_map(Tensor, out)

            return runner

        @functools.partial(jax.jit)
        def compiled_fn(key, *xs):
            with tape_mod.no_grad(), rng_mod.trace_rng_scope(key):
                out = target(*[Tensor(x) if not isinstance(x, Tensor) else x for x in xs])
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor),
            )

        def runner(*xs, **kw):
            arrs = [x._value if isinstance(x, Tensor) else x for x in xs]
            out = compiled_fn(rng_mod.next_rng_key(), *arrs)
            return jax.tree_util.tree_map(Tensor, out)

        return runner


def to_static(layer=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    if layer is None:
        return functools.partial(to_static, input_spec=input_spec)
    traced = TracedLayer(layer, input_spec)
    if hasattr(layer, "named_parameters"):
        # keep Layer interface: attach traced call
        layer.__dict__["_traced"] = traced
        orig_class_call = layer.__class__.__call__

        def patched_call(*args, **kw):
            return traced(*args, **kw)

        layer.__dict__["__traced_call__"] = patched_call
        layer.forward_traced = traced
        return layer
    return traced


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — persists params + a traceable config.

    Reference stores a serialized Program; we store state_dict + class info and
    reconstruct via jit tracing at load (StableHLO export planned round 2).
    """
    from ..framework.io import save as _save

    state = layer.state_dict() if hasattr(layer, "state_dict") else {}
    _save({"state_dict": state, "class": layer.__class__.__name__}, path + ".pdparams")


def load(path, **configs):
    from ..framework.io import load as _load

    return _load(path + ".pdparams")


def not_to_static(fn=None):
    return fn


ignore_module = lambda *a, **k: None
