"""paddle.jit — trace-to-XLA compilation.

Reference analog: dy2static (`python/paddle/fluid/dygraph/dygraph_to_static/`,
ProgramTranslator → run_program op). TPU-native: no AST rewriting — `to_static`
traces the layer/function ONCE with jax, caches the compiled XLA executable per
input signature, and runs it with buffer donation. This is the IPU whole-graph
compile model (§3.5 of the survey) applied to dygraph.
"""
from __future__ import annotations

import functools
import os

import jax
import numpy as np

from ..core import rng as rng_mod
from ..core import tape as tape_mod
from ..core.tensor import Tensor


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


def _sig_of(args):
    sig = []
    for a in args:
        if isinstance(a, Tensor):
            sig.append(("T", tuple(a.shape), str(a._value.dtype)))
        elif isinstance(a, np.ndarray):
            sig.append(("A", a.shape, str(a.dtype)))
        else:
            sig.append(("S", a))
    return tuple(sig)


class TracedLayer:
    """Wraps a Layer or function into a jit-compiled callable with param capture."""

    def __init__(self, fn_or_layer, input_spec=None, donate_buffers=False):
        self._target = fn_or_layer
        self._input_spec = input_spec
        self._cache = {}
        self._is_layer = hasattr(fn_or_layer, "named_parameters")

    def __call__(self, *args, **kwargs):
        if not ProgramTranslator.get_instance().enable_to_static:
            # global dy2static kill-switch: run the original eagerly
            return self._target(*args, **kwargs)
        key = _sig_of(args)
        if key not in self._cache:
            self._cache[key] = self._build(args, kwargs)
        runner = self._cache[key]
        return runner(*args, **kwargs)

    def _build(self, args, kwargs):
        target = self._target
        if self._is_layer:
            params, buffers = target.functional_state()
            p_arrays = {k: v._value for k, v in params.items()}
            b_arrays = {k: v._value for k, v in buffers.items()}

            @functools.partial(jax.jit)
            def compiled(p, b, key, *xs):
                with tape_mod.no_grad(), rng_mod.trace_rng_scope(key):
                    out, new_b = target.functional_call(
                        {k: v for k, v in p.items()}, {k: v for k, v in b.items()}, *xs
                    )
                flat = jax.tree_util.tree_map(
                    lambda t: t._value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor),
                )
                return flat, new_b

            def runner(*xs, **kw):
                arrs = [x._value if isinstance(x, Tensor) else x for x in xs]
                cur_p = {k: v._value for k, v in target.functional_state()[0].items()}
                cur_b = {k: v._value for k, v in target.functional_state()[1].items()}
                key = rng_mod.next_rng_key()
                out, new_b = compiled(cur_p, cur_b, key, *arrs)
                # write back updated buffers (BN running stats)
                _, bufs = target.functional_state()
                for k, v in new_b.items():
                    if k in bufs and bufs[k] is not None:
                        bufs[k]._value = v
                return jax.tree_util.tree_map(Tensor, out)

            return runner

        @functools.partial(jax.jit)
        def compiled_fn(key, *xs):
            with tape_mod.no_grad(), rng_mod.trace_rng_scope(key):
                out = target(*[Tensor(x) if not isinstance(x, Tensor) else x for x in xs])
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor),
            )

        def runner(*xs, **kw):
            arrs = [x._value if isinstance(x, Tensor) else x for x in xs]
            out = compiled_fn(rng_mod.next_rng_key(), *arrs)
            return jax.tree_util.tree_map(Tensor, out)

        return runner


def to_static(layer=None, input_spec=None, build_strategy=None, backend=None,
              convert_control_flow=True, **kwargs):
    if layer is None:
        return functools.partial(to_static, input_spec=input_spec,
                                 convert_control_flow=convert_control_flow)
    if convert_control_flow:
        # dy2static AST pass, always-on like the reference ProgramTranslator
        # (program_translator.py:860): tensor-dependent if/while/for and
        # break/continue survive tracing. Source beyond the conversion
        # subset falls back to the unconverted function (python control
        # flow still works; tensor-dependent flow surfaces jax's
        # tracer-bool error like before).
        from .dy2static import convert_control_flow as _convert

        def _safe_convert(fn):
            try:
                return _convert(fn)
            except Exception as e:  # noqa: BLE001 — conversion must not
                import sys          # break functions it cannot parse

                print(f"[paddle_tpu] dy2static conversion of "
                      f"{getattr(fn, '__name__', fn)!r} failed "
                      f"({type(e).__name__}: {e}); running unconverted",
                      file=sys.stderr)
                return fn

        if hasattr(layer, "named_parameters"):
            converted = _safe_convert(type(layer).forward)
            if converted is not type(layer).forward:
                layer.forward = converted.__get__(layer)
        else:
            layer = _safe_convert(layer)
    traced = TracedLayer(layer, input_spec)
    if hasattr(layer, "named_parameters"):
        # keep Layer interface: attach traced call
        layer.__dict__["_traced"] = traced
        orig_class_call = layer.__class__.__call__

        def patched_call(*args, **kw):
            return traced(*args, **kw)

        layer.__dict__["__traced_call__"] = patched_call
        layer.forward_traced = traced
        return layer
    return traced


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — persists params and, when `input_spec` is given, a
    deployable compiled module.

    Reference analog: `paddle.jit.save` serializes a pruned ProgramDesc +
    persistables (dygraph_to_static/program_translator.py). TPU-native: the
    artifact is the layer's forward lowered to ONE XLA computation with weights
    baked in, serialized via jax.export (StableHLO) — loadable by
    `paddle.jit.load` (TranslatedLayer) and `paddle.inference.Predictor`.
    """
    import pickle

    from ..framework.io import save as _save

    state = layer.state_dict() if hasattr(layer, "state_dict") else {}
    _save({"state_dict": state, "class": layer.__class__.__name__}, path + ".pdparams")

    if input_spec is None or not hasattr(layer, "functional_state"):
        # drop any stale compiled module from an earlier save(input_spec=...) —
        # its baked-in weights no longer match the just-saved .pdparams
        if os.path.exists(path + ".pdmodel"):
            os.remove(path + ".pdmodel")
        return

    from jax import export as jexport

    params, buffers = layer.functional_state()
    p_arrays = {k: v._value for k, v in params.items()}
    b_arrays = {k: (v._value if v is not None else None) for k, v in buffers.items()}

    def fwd(*xs):
        with tape_mod.no_grad(), rng_mod.trace_rng_scope(jax.random.PRNGKey(0)):
            out, _ = layer.functional_call(p_arrays, b_arrays, *xs)
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor),
        )

    import jax.numpy as jnp

    avals = [jax.ShapeDtypeStruct(tuple(s.shape), jnp.dtype(s.dtype))
             for s in input_spec]
    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    exported = jexport.export(jax.jit(fwd))(*avals)
    if was_training and hasattr(layer, "train"):
        layer.train()
    n_out = len(exported.out_avals)
    meta = {
        "magic": "paddle_tpu.jit.v1",
        "stablehlo": exported.serialize(),
        "in_shapes": [tuple(s.shape) for s in input_spec],
        "in_dtypes": [str(s.dtype) for s in input_spec],
        # feed/fetch view so inference.Predictor / load_inference_model can
        # open jit artifacts too (same schema as static/io.py)
        "feed_names": [getattr(s, "name", None) or f"x{i}"
                       for i, s in enumerate(input_spec)],
        "feed_shapes": [tuple(s.shape) for s in input_spec],
        "feed_dtypes": [str(s.dtype) for s in input_spec],
        "fetch_names": [f"out{i}" for i in range(n_out)],
    }
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f, protocol=4)


class TranslatedLayer:
    """reference: fluid/dygraph/io.py TranslatedLayer — a loaded, compiled,
    inference-only module."""

    def __init__(self, meta):
        from jax import export as jexport

        self._meta = meta
        self._exported = jexport.deserialize(meta["stablehlo"])
        self.training = False

    def __call__(self, *xs):
        import jax.numpy as jnp

        args = []
        for x, dt in zip(xs, self._meta["in_dtypes"]):
            a = x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
            args.append(a.astype(dt) if str(a.dtype) != dt else a)
        out = self._exported.call(*args)
        if isinstance(out, (list, tuple)):
            outs = [Tensor(o) for o in out]
            return outs[0] if len(outs) == 1 else outs
        return Tensor(out)

    forward = __call__

    def eval(self):
        return self

    def train(self):  # pragma: no cover - parity shim
        raise RuntimeError("TranslatedLayer is inference-only; finetune from "
                           "the .pdparams state_dict instead")


def load(path, **configs):
    import os
    import pickle

    from ..framework.io import load as _load

    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            head = f.read(1)
        if head != b"\x80":  # REAL Paddle ProgramDesc protobuf
            from ..inference.pdmodel import load_pdmodel

            return _PdModelLayer(load_pdmodel(
                path, params_file=configs.get("params_filename")))
        with open(path + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
        if meta.get("magic") == "paddle_tpu.jit.v1":
            return TranslatedLayer(meta)
    return _load(path + ".pdparams")


class _PdModelLayer:
    """TranslatedLayer-shaped callable over a real .pdmodel (jit.load on a
    model exported by real paddle.jit.save)."""

    def __init__(self, prog):
        self._prog = prog
        self.training = False

    def __call__(self, *inputs):
        from ..core.tensor import Tensor

        feed = {}
        for name, x in zip(self._prog.feed_names, inputs):
            feed[name] = x.numpy() if isinstance(x, Tensor) else x
        outs = [Tensor(o) for o in self._prog.run(feed)]
        return outs[0] if len(outs) == 1 else tuple(outs)

    forward = __call__

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError(
            "a loaded .pdmodel is an inference program; training requires "
            "the dygraph model + .pdparams (paddle.load)")


def not_to_static(fn=None):
    return fn


ignore_module = lambda *a, **k: None


# ---- parity shims (reference: jit/__init__.py ProgramTranslator + logging) --
class ProgramTranslator:
    """Singleton controlling dy2static globally (reference
    dygraph_to_static/program_translator.py): enable(False) makes to_static
    functions run eagerly."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enable_to_static = True
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static: bool):
        self.enable_to_static = bool(enable_to_static)


def set_code_level(level=100, also_to_stdout=False):
    """Dump transformed code at/below `level` (reference jit.set_code_level).
    Maps onto the dy2static debug flag."""
    os.environ["PADDLE_TPU_D2S_CODE_LEVEL"] = str(level)


def set_verbosity(level=0, also_to_stdout=False):
    """Set dy2static logging verbosity (reference jit.set_verbosity)."""
    os.environ["PADDLE_TPU_D2S_VERBOSITY"] = str(level)
