"""Parity layer batch (reference: python/paddle/nn/layer/{pooling,conv,loss,
common,vision}.py classes absent from the earlier modules). Thin wrappers over
nn.functional following the same conventions as layers_pooling/layers_conv."""
from __future__ import annotations

import numpy as np

from . import functional as F
from . import initializer as I
from .layer import Layer
from .layers_conv import _pair


# ------------------------------------------------------------------- pooling
class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, return_mask)

    def forward(self, x):
        k, s, p, cm, rm = self._args
        return F.max_pool3d(x, k, s, p, ceil_mode=cm, return_mask=rm)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, exclusive)

    def forward(self, x):
        k, s, p, cm, ex = self._args
        return F.avg_pool3d(x, k, s, p, ceil_mode=cm, exclusive=ex)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size,
                                     return_mask=self._return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size,
                                     return_mask=self._return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self._args
        return F.max_unpool1d(x, indices, k, s, p, output_size=o)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self._args
        return F.max_unpool2d(x, indices, k, s, p, output_size=o)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self._args
        return F.max_unpool3d(x, indices, k, s, p, output_size=o)


# ------------------------------------------------------------------- conv
class _ConvTransposeNd(Layer):
    ND = 1
    FN = staticmethod(F.conv1d_transpose)

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        nd = self.ND
        self._stride = _pair(stride, nd)
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = _pair(dilation, nd)
        self._groups = groups
        k = _pair(kernel_size, nd)
        fan_in = in_channels * int(np.prod(k))
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *k), attr=weight_attr,
            default_initializer=I.KaimingUniform(
                fan_in=fan_in, negative_slope=np.sqrt(5.0),
                nonlinearity="leaky_relu"))
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))

    def forward(self, x, output_size=None):
        return type(self).FN(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation, output_size)


class Conv1DTranspose(_ConvTransposeNd):
    ND = 1
    FN = staticmethod(F.conv1d_transpose)


class Conv3DTranspose(_ConvTransposeNd):
    ND = 3
    FN = staticmethod(F.conv3d_transpose)


# ------------------------------------------------------------------- vision
class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._groups = groups
        self._data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self._groups, self._data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = downscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._factor, self._data_format)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self._padding = padding
        self._data_format = data_format

    def forward(self, x):
        return F.zeropad2d(x, self._padding, self._data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self._args)


# ------------------------------------------------------------------- misc
class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._p, self._eps, self._keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        import jax.numpy as jnp

        from ..core.dispatch import primitive_call

        def f(a, b):
            d = a - b + self._eps
            return jnp.sum(jnp.abs(d) ** self._p, axis=-1,
                           keepdims=self._keepdim) ** (1.0 / self._p)

        return primitive_call(f, x, y, name="pairwise_distance")


# ------------------------------------------------------------------- losses
class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self._blank, reduction=self._reduction,
                          norm_by_times=norm_by_times)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError("custom trees not supported yet")
        self._num_classes = num_classes
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_classes - 1,), attr=bias_attr, is_bias=True)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias)
