"""Conv layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from . import functional as F
from . import initializer as I
from .layer import Layer


def _pair(v, n=2):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _pair(kernel_size, nd)
        self._stride = _pair(stride, nd)
        self._padding = padding
        self._dilation = _pair(dilation, nd)
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels * int(np.prod(self._kernel_size)) // groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *self._kernel_size),
            attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in, negative_slope=np.sqrt(5.0), nonlinearity="leaky_relu"),
        )
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound),
        )

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={list(self._kernel_size)}, stride={list(self._stride)}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self._stride = _pair(stride)
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = _pair(dilation)
        self._groups = groups
        self._data_format = data_format
        k = _pair(kernel_size)
        fan_in = in_channels * int(np.prod(k))
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *k), attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in, negative_slope=np.sqrt(5.0), nonlinearity="leaky_relu"),
        )
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound),
        )

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                  self._output_padding, self._dilation, self._groups,
                                  output_size, self._data_format)
