"""Weight initializers (reference: python/paddle/nn/initializer/, fluid/initializer.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import to_jax_dtype
from ..core.rng import next_rng_key

__all__ = [
    "Bilinear", "set_global_initializer",
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *k]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a**2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = to_jax_dtype(dtype)
        return jax.random.normal(next_rng_key(), shape, dt) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = to_jax_dtype(dtype)
        return (
            jax.random.truncated_normal(next_rng_key(), -2.0, 2.0, shape, dt) * self.std
            + self.mean
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_rng_key(), shape, to_jax_dtype(dtype), self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(next_rng_key(), shape, to_jax_dtype(dtype)) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_rng_key(), shape, to_jax_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(next_rng_key(), shape, to_jax_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_rng_key(), shape, to_jax_dtype(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ..core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype=to_jax_dtype(dtype))
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return jax.nn.initializers.orthogonal(self.gain)(
            next_rng_key(), shape, to_jax_dtype(dtype)
        )


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        w = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        k = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            w[(i, i % ic) + tuple(k)] = 1.0
        return jnp.asarray(w, dtype=to_jax_dtype(dtype))


class Bilinear(Initializer):
    """reference: nn/initializer/Bilinear (fluid/initializer.py
    BilinearInitializer) — bilinear-upsample kernels for conv-transpose:
    weight[c_out, c_in, kh, kw] gets a separable triangular kernel."""

    def __call__(self, shape, dtype=jnp.float32, key=None):
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError(
                f"Bilinear initializer needs 4-D conv weights, got {shape}")
        kh, kw = shape[2], shape[3]

        def tri(k):
            f = np.ceil(k / 2.0)
            c = (2 * f - 1 - f % 2) / (2.0 * f)
            x = np.arange(k)
            return 1 - np.abs(x / f - c)

        kernel = np.outer(tri(kh), tri(kw)).astype(np.float32)
        w = np.zeros(shape, np.float32)
        w[:, :] = kernel  # every (out, in) channel pair shares the kernel
        return jnp.asarray(w, dtype)


_GLOBAL_INIT = [None, None]  # (weight_init, bias_init)


def set_global_initializer(weight_init, bias_init=None):
    """reference: nn/initializer/set_global_initializer — default
    initializers for parameters created afterwards (layers consult
    _global_default when no explicit initializer is given)."""
    if weight_init is not None and not isinstance(weight_init, Initializer):
        raise TypeError("weight_init must be an Initializer or None")
    if bias_init is not None and not isinstance(bias_init, Initializer):
        raise TypeError("bias_init must be an Initializer or None")
    _GLOBAL_INIT[0] = weight_init
    _GLOBAL_INIT[1] = bias_init


def _global_default(is_bias=False):
    return _GLOBAL_INIT[1 if is_bias else 0]


def _set_global_initializer(weight_init, bias_init=None):  # fluid shim hook
    set_global_initializer(weight_init, bias_init)
