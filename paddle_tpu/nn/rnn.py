"""Recurrent layers via lax.scan (reference: python/paddle/nn/layer/rnn.py).

TPU-native: the time loop is a `jax.lax.scan` inside one primitive — XLA compiles
the whole sequence into a single fused loop instead of per-step op dispatch.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor
from . import initializer as I
from .layer import Layer


class _RNNBase(Layer):
    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirectional else 1
        self.num_directions = num_dirs
        gate_mult = {"RNN": 1, "GRU": 3, "LSTM": 4}[self.MODE]
        std = 1.0 / math.sqrt(hidden_size)
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                sfx = f"_reverse" if d else ""
                self.add_parameter(
                    f"weight_ih_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size, in_sz),
                                          attr=weight_ih_attr,
                                          default_initializer=I.Uniform(-std, std)),
                )
                self.add_parameter(
                    f"weight_hh_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size, hidden_size),
                                          attr=weight_hh_attr,
                                          default_initializer=I.Uniform(-std, std)),
                )
                self.add_parameter(
                    f"bias_ih_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size,), attr=bias_ih_attr,
                                          is_bias=True, default_initializer=I.Uniform(-std, std)),
                )
                self.add_parameter(
                    f"bias_hh_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size,), attr=bias_hh_attr,
                                          is_bias=True, default_initializer=I.Uniform(-std, std)),
                )

    def _cell(self, x_t, state, w_ih, w_hh, b_ih, b_hh):
        raise NotImplementedError

    def _layer_params(self, layer, reverse):
        sfx = "_reverse" if reverse else ""
        return (
            self._parameters[f"weight_ih_l{layer}{sfx}"],
            self._parameters[f"weight_hh_l{layer}{sfx}"],
            self._parameters[f"bias_ih_l{layer}{sfx}"],
            self._parameters[f"bias_hh_l{layer}{sfx}"],
        )

    def forward(self, inputs, initial_states=None, sequence_length=None):
        has_cell = self.MODE == "LSTM"
        batch_axis = 1 if self.time_major else 0
        x = inputs
        b = x.shape[batch_axis]
        nl, nd, h = self.num_layers, self.num_directions, self.hidden_size

        if initial_states is None:
            z = Tensor(jnp.zeros((nl * nd, b, h), x._value.dtype))
            initial_states = (z, z.clone()) if has_cell else z

        mode = self.MODE
        time_major = self.time_major

        def run(xv, h0v, c0v, *flat_params):
            if not time_major:
                xv = jnp.swapaxes(xv, 0, 1)  # -> [T, B, ...]
            layer_in = xv
            hs, cs = [], []
            p_iter = iter(flat_params)
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    w_ih, w_hh, b_ih, b_hh = (next(p_iter) for _ in range(4))
                    sidx = layer * nd + d
                    h_init = h0v[sidx]
                    c_init = c0v[sidx] if has_cell else None
                    seq = jnp.flip(layer_in, 0) if d else layer_in

                    def step(carry, x_t, w_ih=w_ih, w_hh=w_hh, b_ih=b_ih, b_hh=b_hh):
                        return _cell_step(mode, carry, x_t, w_ih, w_hh, b_ih, b_hh)

                    carry0 = (h_init, c_init) if has_cell else h_init
                    carry_f, outs = jax.lax.scan(step, carry0, seq)
                    if d:
                        outs = jnp.flip(outs, 0)
                    dir_outs.append(outs)
                    if has_cell:
                        hs.append(carry_f[0])
                        cs.append(carry_f[1])
                    else:
                        hs.append(carry_f)
                layer_in = jnp.concatenate(dir_outs, axis=-1) if nd == 2 else dir_outs[0]
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_n = jnp.stack(hs, 0)
            if has_cell:
                return out, h_n, jnp.stack(cs, 0)
            return out, h_n

        flat_params = []
        for layer in range(nl):
            for d in range(nd):
                flat_params.extend(self._layer_params(layer, bool(d)))

        if has_cell:
            h0, c0 = initial_states
            res = primitive_call(run, x, h0, c0, *flat_params, name=f"{mode}_forward")
            out, h_n, c_n = res
            return out, (h_n, c_n)
        h0 = initial_states
        zero_c = Tensor(jnp.zeros_like(h0._value))
        res = primitive_call(
            lambda xv, h0v, *ps: run(xv, h0v, None, *ps), x, h0, *flat_params,
            name=f"{mode}_forward",
        )
        out, h_n = res
        return out, h_n


def _cell_step(mode, carry, x_t, w_ih, w_hh, b_ih, b_hh):
    if mode == "LSTM":
        h, c = carry
        gates = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new
    if mode == "GRU":
        h = carry
        gi = x_t @ w_ih.T + b_ih
        gh = h @ w_hh.T + b_hh
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n_ = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n_)
        h_new = (1 - z) * n + z * h
        return h_new, h_new
    h = carry
    h_new = jnp.tanh(x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh)
    return h_new, h_new


class SimpleRNN(_RNNBase):
    MODE = "RNN"


class GRU(_RNNBase):
    MODE = "GRU"


class LSTM(_RNNBase):
    MODE = "LSTM"


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter((4 * hidden_size,), is_bias=True,
                                             default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter((4 * hidden_size,), is_bias=True,
                                             default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            z = Tensor(jnp.zeros((inputs.shape[0], self.hidden_size), inputs._value.dtype))
            states = (z, z.clone())
        h, c = states

        def f(x_t, hv, cv, w_ih, w_hh, b_ih, b_hh):
            (h_new, c_new), _ = _cell_step("LSTM", (hv, cv), x_t, w_ih, w_hh, b_ih, b_hh)
            return h_new, c_new

        h_new, c_new = primitive_call(
            f, inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh
        )
        return h_new, (h_new, c_new)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, name=None, **kw):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter((3 * hidden_size,), is_bias=True,
                                             default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter((3 * hidden_size,), is_bias=True,
                                             default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            states = Tensor(jnp.zeros((inputs.shape[0], self.hidden_size), inputs._value.dtype))

        def f(x_t, hv, w_ih, w_hh, b_ih, b_hh):
            h_new, _ = _cell_step("GRU", hv, x_t, w_ih, w_hh, b_ih, b_hh)
            return h_new

        h_new = primitive_call(
            f, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh
        )
        return h_new, h_new


class RNNCellBase(Layer):
    """Base for single-step cells (reference RNNCellBase): provides
    get_initial_states; subclasses implement forward(inputs, states) ->
    (outputs, new_states) and state_shape."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape if shape is not None else self.state_shape
        dt = (batch_ref._value.dtype if isinstance(batch_ref, Tensor)
              else jnp.float32) if dtype is None else dtype

        def make(s):
            return Tensor(jnp.full((batch,) + tuple(
                int(e) for e in (s if isinstance(s, (list, tuple)) else [s])),
                init_value, dt))

        if isinstance(shape, (list, tuple)) and shape and \
                isinstance(shape[0], (list, tuple)):
            return tuple(make(s) for s in shape)
        return make(shape)

    @property
    def state_shape(self):
        raise NotImplementedError


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh) (reference SimpleRNNCell)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.activation = activation
        self.weight_ih = self.create_parameter(
            (hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            (hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            (hidden_size,), attr=bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            (hidden_size,), attr=bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x_t, hv, w_ih, w_hh, b_ih, b_hh):
            return act(x_t @ w_ih.T + b_ih + hv @ w_hh.T + b_hh)

        h_new = primitive_call(
            f, inputs, states, self.weight_ih, self.weight_hh,
            self.bias_ih, self.bias_hh)
        return h_new, h_new


class RNN(Layer):
    """Drive a single-step cell over a sequence (reference paddle.nn.RNN).

    The time loop is a Python loop over the (static) sequence length —
    generic cells hold arbitrary Python state, so XLA sees an unrolled
    chain; the fused-scan path lives in SimpleRNN/GRU/LSTM (_RNNBase)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        x = inputs if self.time_major else inputs.transpose(
            [1, 0] + list(range(2, len(inputs.shape))))
        T = x.shape[0]
        states = initial_states
        if states is None and hasattr(self.cell, "get_initial_states"):
            batch_ref = x[0]
            states = self.cell.get_initial_states(batch_ref)
        L = None
        if sequence_length is not None:
            L = sequence_length._value if isinstance(sequence_length, Tensor) \
                else jnp.asarray(sequence_length)

        def freeze(new, old, valid):
            """Keep `old` state for rows already past their length — pad
            steps must not pollute state (reference masks updates; for the
            reverse direction this makes the pass an exact reverse over each
            row's valid prefix: state stays initial until t < L)."""
            def leaf(n, o):
                nv = n._value if isinstance(n, Tensor) else n
                ov = o._value if isinstance(o, Tensor) else o
                m = valid.reshape((-1,) + (1,) * (nv.ndim - 1))
                return Tensor(jnp.where(m, nv, ov))

            return jax.tree_util.tree_map(
                leaf, new, old, is_leaf=lambda v: isinstance(v, Tensor))

        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        outs = [None] * T
        for t in steps:
            out, new_states = self.cell(x[t], states, **kwargs)
            if L is not None:
                valid = t < L
                states = freeze(new_states, states, valid)
            else:
                states = new_states
            outs[t] = out
        from ..tensor_ops.manipulation import stack

        y = stack(outs, axis=0 if self.time_major else 1)
        if L is not None:
            # zero outputs past each row's length (reference masks them)
            t_idx = jnp.arange(T)
            mask = (t_idx[:, None] < L[None, :]) if self.time_major else \
                (t_idx[None, :] < L[:, None])
            mask = mask[..., None].astype(y._value.dtype)
            y = Tensor(y._value * mask)
        return y, states


class BiRNN(Layer):
    """Forward + backward cells over the same input, outputs concatenated
    (reference paddle.nn.BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        y_fw, s_fw = self.rnn_fw(inputs, st_fw, sequence_length, **kwargs)
        y_bw, s_bw = self.rnn_bw(inputs, st_bw, sequence_length, **kwargs)
        from ..tensor_ops.manipulation import concat

        return concat([y_fw, y_bw], axis=-1), (s_fw, s_bw)
