"""Recurrent layers via lax.scan (reference: python/paddle/nn/layer/rnn.py).

TPU-native: the time loop is a `jax.lax.scan` inside one primitive — XLA compiles
the whole sequence into a single fused loop instead of per-step op dispatch.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor
from . import initializer as I
from .layer import Layer


class _RNNBase(Layer):
    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirectional else 1
        self.num_directions = num_dirs
        gate_mult = {"RNN": 1, "GRU": 3, "LSTM": 4}[self.MODE]
        std = 1.0 / math.sqrt(hidden_size)
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                sfx = f"_reverse" if d else ""
                self.add_parameter(
                    f"weight_ih_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size, in_sz),
                                          attr=weight_ih_attr,
                                          default_initializer=I.Uniform(-std, std)),
                )
                self.add_parameter(
                    f"weight_hh_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size, hidden_size),
                                          attr=weight_hh_attr,
                                          default_initializer=I.Uniform(-std, std)),
                )
                self.add_parameter(
                    f"bias_ih_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size,), attr=bias_ih_attr,
                                          is_bias=True, default_initializer=I.Uniform(-std, std)),
                )
                self.add_parameter(
                    f"bias_hh_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size,), attr=bias_hh_attr,
                                          is_bias=True, default_initializer=I.Uniform(-std, std)),
                )

    def _cell(self, x_t, state, w_ih, w_hh, b_ih, b_hh):
        raise NotImplementedError

    def _layer_params(self, layer, reverse):
        sfx = "_reverse" if reverse else ""
        return (
            self._parameters[f"weight_ih_l{layer}{sfx}"],
            self._parameters[f"weight_hh_l{layer}{sfx}"],
            self._parameters[f"bias_ih_l{layer}{sfx}"],
            self._parameters[f"bias_hh_l{layer}{sfx}"],
        )

    def forward(self, inputs, initial_states=None, sequence_length=None):
        has_cell = self.MODE == "LSTM"
        batch_axis = 1 if self.time_major else 0
        x = inputs
        b = x.shape[batch_axis]
        nl, nd, h = self.num_layers, self.num_directions, self.hidden_size

        if initial_states is None:
            z = Tensor(jnp.zeros((nl * nd, b, h), x._value.dtype))
            initial_states = (z, z.clone()) if has_cell else z

        mode = self.MODE
        time_major = self.time_major

        def run(xv, h0v, c0v, *flat_params):
            if not time_major:
                xv = jnp.swapaxes(xv, 0, 1)  # -> [T, B, ...]
            layer_in = xv
            hs, cs = [], []
            p_iter = iter(flat_params)
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    w_ih, w_hh, b_ih, b_hh = (next(p_iter) for _ in range(4))
                    sidx = layer * nd + d
                    h_init = h0v[sidx]
                    c_init = c0v[sidx] if has_cell else None
                    seq = jnp.flip(layer_in, 0) if d else layer_in

                    def step(carry, x_t, w_ih=w_ih, w_hh=w_hh, b_ih=b_ih, b_hh=b_hh):
                        return _cell_step(mode, carry, x_t, w_ih, w_hh, b_ih, b_hh)

                    carry0 = (h_init, c_init) if has_cell else h_init
                    carry_f, outs = jax.lax.scan(step, carry0, seq)
                    if d:
                        outs = jnp.flip(outs, 0)
                    dir_outs.append(outs)
                    if has_cell:
                        hs.append(carry_f[0])
                        cs.append(carry_f[1])
                    else:
                        hs.append(carry_f)
                layer_in = jnp.concatenate(dir_outs, axis=-1) if nd == 2 else dir_outs[0]
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_n = jnp.stack(hs, 0)
            if has_cell:
                return out, h_n, jnp.stack(cs, 0)
            return out, h_n

        flat_params = []
        for layer in range(nl):
            for d in range(nd):
                flat_params.extend(self._layer_params(layer, bool(d)))

        if has_cell:
            h0, c0 = initial_states
            res = primitive_call(run, x, h0, c0, *flat_params, name=f"{mode}_forward")
            out, h_n, c_n = res
            return out, (h_n, c_n)
        h0 = initial_states
        zero_c = Tensor(jnp.zeros_like(h0._value))
        res = primitive_call(
            lambda xv, h0v, *ps: run(xv, h0v, None, *ps), x, h0, *flat_params,
            name=f"{mode}_forward",
        )
        out, h_n = res
        return out, h_n


def _cell_step(mode, carry, x_t, w_ih, w_hh, b_ih, b_hh):
    if mode == "LSTM":
        h, c = carry
        gates = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new
    if mode == "GRU":
        h = carry
        gi = x_t @ w_ih.T + b_ih
        gh = h @ w_hh.T + b_hh
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n_ = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n_)
        h_new = (1 - z) * n + z * h
        return h_new, h_new
    h = carry
    h_new = jnp.tanh(x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh)
    return h_new, h_new


class SimpleRNN(_RNNBase):
    MODE = "RNN"


class GRU(_RNNBase):
    MODE = "GRU"


class LSTM(_RNNBase):
    MODE = "LSTM"


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter((4 * hidden_size,), is_bias=True,
                                             default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter((4 * hidden_size,), is_bias=True,
                                             default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            z = Tensor(jnp.zeros((inputs.shape[0], self.hidden_size), inputs._value.dtype))
            states = (z, z.clone())
        h, c = states

        def f(x_t, hv, cv, w_ih, w_hh, b_ih, b_hh):
            (h_new, c_new), _ = _cell_step("LSTM", (hv, cv), x_t, w_ih, w_hh, b_ih, b_hh)
            return h_new, c_new

        h_new, c_new = primitive_call(
            f, inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh
        )
        return h_new, (h_new, c_new)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, name=None, **kw):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter((3 * hidden_size,), is_bias=True,
                                             default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter((3 * hidden_size,), is_bias=True,
                                             default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            states = Tensor(jnp.zeros((inputs.shape[0], self.hidden_size), inputs._value.dtype))

        def f(x_t, hv, w_ih, w_hh, b_ih, b_hh):
            h_new, _ = _cell_step("GRU", hv, x_t, w_ih, w_hh, b_ih, b_hh)
            return h_new

        h_new = primitive_call(
            f, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh
        )
        return h_new, h_new
