"""nn.functional — neural-net ops lowered to XLA (reference: python/paddle/nn/functional/).

Conv/pool map to lax.conv_general_dilated / reduce_window (MXU + fused by XLA);
attention has a Pallas flash-attention fast path (paddle_tpu/kernels/) gated by
FLAGS_use_pallas_kernels when running on real TPU.
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.rng import next_rng_key
from ..core.tensor import Tensor

__all__ = [
    "relu", "relu6", "gelu", "sigmoid", "tanh", "softmax", "log_softmax",
    "leaky_relu", "elu", "selu", "silu", "swish", "hardswish", "hardsigmoid",
    "hardtanh", "mish", "softplus", "softsign", "tanhshrink", "softshrink",
    "hardshrink", "prelu", "glu", "maxout",
    "linear", "conv1d", "conv2d", "conv3d", "conv2d_transpose",
    "max_pool1d", "max_pool2d", "avg_pool1d", "avg_pool2d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_max_pool2d",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "local_response_norm",
    "embedding", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "cross_entropy", "softmax_with_cross_entropy", "linear_cross_entropy",
    "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_similarity", "normalize", "label_smooth", "one_hot", "pad",
    "interpolate", "upsample", "pixel_shuffle", "unfold", "grid_sample",
    "scaled_dot_product_attention", "sequence_mask", "temperature_scaled_softmax",
    "rrelu", "celu", "logsigmoid", "gumbel_softmax", "square_error_cost",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


# ------------------------------------------------------------------ activations
def relu(x, name=None):
    return primitive_call(jax.nn.relu, _t(x), name="relu")


def relu6(x, name=None):
    return primitive_call(jax.nn.relu6, _t(x), name="relu6")


def gelu(x, approximate=False, name=None):
    return primitive_call(lambda a: jax.nn.gelu(a, approximate=approximate), _t(x), name="gelu")


def sigmoid(x, name=None):
    return primitive_call(jax.nn.sigmoid, _t(x), name="sigmoid")


def logsigmoid(x, name=None):
    return primitive_call(jax.nn.log_sigmoid, _t(x), name="logsigmoid")


def tanh(x, name=None):
    return primitive_call(jnp.tanh, _t(x), name="tanh")


def softmax(x, axis=-1, dtype=None, name=None):
    from ..core.dtype import to_jax_dtype

    def f(a):
        if dtype is not None:
            a = a.astype(to_jax_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)

    return primitive_call(f, _t(x), name="softmax")


def temperature_scaled_softmax(x, temperature=1.0, axis=-1, name=None):
    return primitive_call(lambda a: jax.nn.softmax(a / temperature, axis=axis), _t(x))


def log_softmax(x, axis=-1, dtype=None, name=None):
    return primitive_call(lambda a: jax.nn.log_softmax(a, axis=axis), _t(x), name="log_softmax")


def leaky_relu(x, negative_slope=0.01, name=None):
    return primitive_call(lambda a: jax.nn.leaky_relu(a, negative_slope), _t(x))


def elu(x, alpha=1.0, name=None):
    return primitive_call(lambda a: jax.nn.elu(a, alpha), _t(x))


def celu(x, alpha=1.0, name=None):
    return primitive_call(lambda a: jax.nn.celu(a, alpha), _t(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return primitive_call(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), _t(x))


def silu(x, name=None):
    return primitive_call(jax.nn.silu, _t(x), name="silu")


def swish(x, name=None):
    return silu(x)


def hardswish(x, name=None):
    return primitive_call(lambda a: a * jnp.clip(a + 3, 0, 6) / 6, _t(x))


def hardsigmoid(x, slope=1 / 6, offset=0.5, name=None):
    return primitive_call(lambda a: jnp.clip(a * slope + offset, 0, 1), _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return primitive_call(lambda a: jnp.clip(a, min, max), _t(x))


def mish(x, name=None):
    return primitive_call(lambda a: a * jnp.tanh(jax.nn.softplus(a)), _t(x))


def softplus(x, beta=1, threshold=20, name=None):
    return primitive_call(
        lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta), _t(x)
    )


def softsign(x, name=None):
    return primitive_call(jax.nn.soft_sign, _t(x))


def tanhshrink(x, name=None):
    return primitive_call(lambda a: a - jnp.tanh(a), _t(x))


def softshrink(x, threshold=0.5, name=None):
    return primitive_call(
        lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)),
        _t(x),
    )


def hardshrink(x, threshold=0.5, name=None):
    return primitive_call(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return primitive_call(f, _t(x), _t(weight), name="prelu")


def rrelu(x, lower=0.125, upper=0.333, training=True, name=None):
    if training:
        a = jax.random.uniform(next_rng_key(), (), float, lower, upper)
    else:
        a = (lower + upper) / 2
    return primitive_call(lambda v: jnp.where(v >= 0, v, a * v), _t(x))


def glu(x, axis=-1, name=None):
    return primitive_call(lambda a: jax.nn.glu(a, axis=axis), _t(x))


def maxout(x, groups, axis=1, name=None):
    def f(a):
        shape = list(a.shape)
        c = shape[axis]
        new_shape = shape[:axis] + [groups, c // groups] + shape[axis + 1 :]
        return jnp.max(a.reshape(new_shape), axis=axis)

    return primitive_call(f, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = jax.random.gumbel(next_rng_key(), tuple(x.shape))

    def f(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = y_hard + jax.lax.stop_gradient(y) - y  # straight-through... (swap)
            y = y_hard - jax.lax.stop_gradient(y_hard) + jax.nn.softmax((a + g) / temperature, axis=axis)
        return y

    return primitive_call(f, _t(x))


# ------------------------------------------------------------------ linear/conv
def linear(x, weight, bias=None, name=None):
    if bias is None:
        return primitive_call(lambda a, w: a @ w, _t(x), _t(weight), name="linear")
    return primitive_call(lambda a, w, b: a @ w + b, _t(x), _t(weight), _t(bias), name="linear")


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _conv_padding(padding, k, dilation, n):
    """Return lax-style padding config for int / list / SAME / VALID."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad = _conv_padding(padding, None, dilation, 2)
    dn = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")

    def f(a, w, *b):
        if data_format == "NHWC":
            w = jnp.transpose(w, (2, 3, 1, 0))
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=jnp.float32 if a.dtype == jnp.float32 else None,
        )
        if b:
            bias_shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
            out = out + b[0].reshape(bias_shape)
        return out.astype(a.dtype)

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="conv2d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    stride = _pair(stride, 1)
    dilation = _pair(dilation, 1)
    pad = _conv_padding(padding, None, dilation, 1)

    def f(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=("NCH", "OIH", "NCH"), feature_group_count=groups,
        )
        if b:
            out = out + b[0].reshape(1, -1, 1)
        return out.astype(a.dtype)

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="conv1d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    pad = _conv_padding(padding, None, dilation, 3)

    def f(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"), feature_group_count=groups,
        )
        if b:
            out = out + b[0].reshape(1, -1, 1, 1, 1)
        return out.astype(a.dtype)

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="conv3d")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCHW", name=None):
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad_cfg = padding

    def f(a, w, *b):
        # weight layout IOHW (paddle convention for transpose conv: [in, out/groups, H, W])
        kh, kw = w.shape[2], w.shape[3]
        if isinstance(pad_cfg, int):
            pads = [(pad_cfg, pad_cfg), (pad_cfg, pad_cfg)]
        elif isinstance(pad_cfg, str):
            pads = pad_cfg.upper()
        else:
            pads = _conv_padding(pad_cfg, None, dilation, 2)
        if isinstance(pads, list):
            # lax.conv_transpose padding semantics: pad the *output*; convert
            lax_pads = [
                (dilation[i] * (k - 1) - p[0], dilation[i] * (k - 1) - p[1])
                for i, (p, k) in enumerate(zip(pads, (kh, kw)))
            ]
        else:
            lax_pads = pads
        w_t = jnp.transpose(w, (1, 0, 2, 3))  # -> OIHW with O=out
        w_t = jnp.flip(w_t, axis=(2, 3))
        out = jax.lax.conv_general_dilated(
            a, w_t, window_strides=(1, 1), padding=lax_pads, lhs_dilation=stride,
            rhs_dilation=dilation, dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=groups,
        )
        if b:
            out = out + b[0].reshape(1, -1, 1, 1)
        return out.astype(a.dtype)

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="conv2d_transpose")


# ------------------------------------------------------------------ pooling
def _pool(x, kernel, stride, padding, reducer, init, data_format="NCHW", avg=False,
          ceil_mode=False, exclusive=True, nd=2):
    kernel = _pair(kernel, nd)
    stride = _pair(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _pair(padding, nd)
        pad = [(0, 0), (0, 0)] + [(pi, pi) for pi in p]
    spatial_off = 2 if data_format.startswith("NC") else 1
    window = [1] * spatial_off + list(kernel) + ([1] if not data_format.startswith("NC") else [])
    strides = [1] * spatial_off + list(stride) + ([1] if not data_format.startswith("NC") else [])
    if data_format.startswith("NC"):
        window = [1, 1] + list(kernel)
        strides = [1, 1] + list(stride)
    if isinstance(pad, list) and not data_format.startswith("NC"):
        pad = [(0, 0)] + pad[2:] + [(0, 0)]
    if ceil_mode and isinstance(pad, list):
        # extra right/bottom padding so the last partial window is kept:
        # out = ceil((n + 2p - k)/s) + 1 instead of floor (+1)
        shape = tuple(_t(x).shape) if hasattr(x, "shape") else None
        if shape is not None:
            spatial_dims = ([d for d in range(2, 2 + nd)]
                            if data_format.startswith("NC")
                            else [d for d in range(1, 1 + nd)])
            pad = list(pad)
            for i, d in enumerate(spatial_dims):
                n = int(shape[d]) + pad[d][0] + pad[d][1] - kernel[i]
                rem = n % stride[i]
                if rem:
                    pad[d] = (pad[d][0], pad[d][1] + stride[i] - rem)

    def f(a):
        out = jax.lax.reduce_window(a, init, reducer, window, strides, pad)
        if avg:
            if not exclusive:
                # divide by the full kernel size, counting padded zeros
                # (reference: pool_op exclusive=False)
                return out / float(np.prod(kernel))
            if not isinstance(pad, str) and all(p == (0, 0) for p in pad):
                return out / float(np.prod(kernel))
            # exclusive: divide by the number of real (non-pad) elements
            counts = jax.lax.reduce_window(
                jnp.ones_like(a), 0.0, jax.lax.add, window, strides, pad
            )
            return out / counts
        return out

    return primitive_call(f, _t(x), name="pool")


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, jax.lax.max, -jnp.inf, data_format,
                 ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, jax.lax.add, 0.0, data_format,
                 avg=True, ceil_mode=ceil_mode, exclusive=exclusive)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, jax.lax.max, -jnp.inf, "NCL",
                 ceil_mode=ceil_mode, nd=1)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, jax.lax.add, 0.0, "NCL",
                 avg=True, ceil_mode=ceil_mode, exclusive=exclusive, nd=1)


def _adaptive_bins(n, out):
    """Torch/paddle adaptive bins: bin i = [floor(i*n/out), ceil((i+1)*n/out))."""
    starts = [(i * n) // out for i in range(out)]
    ends = [-(-((i + 1) * n) // out) for i in range(out)]
    return starts, ends


def _adaptive_avg_matrix(n, out, dtype):
    """(out, n) averaging matrix — adaptive pooling as a matmul (MXU-friendly)."""
    m = np.zeros((out, n), np.float64)
    starts, ends = _adaptive_bins(n, out)
    for i, (s, e) in enumerate(zip(starts, ends)):
        m[i, s:e] = 1.0 / (e - s)
    return m.astype(dtype)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_hw = _pair(output_size)

    def f(a):
        nchw = data_format == "NCHW"
        h, w = (a.shape[2], a.shape[3]) if nchw else (a.shape[1], a.shape[2])
        oh = h if out_hw[0] is None else out_hw[0]
        ow = w if out_hw[1] is None else out_hw[1]
        if h % oh == 0 and w % ow == 0:
            kh, kw = h // oh, w // ow
            window = (1, 1, kh, kw) if nchw else (1, kh, kw, 1)
            out = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, window, "VALID")
            return out / (kh * kw)
        mh = jnp.asarray(_adaptive_avg_matrix(h, oh, a.dtype))
        mw = jnp.asarray(_adaptive_avg_matrix(w, ow, a.dtype))
        if nchw:
            return jnp.einsum("nchw,oh,pw->ncop", a, mh, mw)
        return jnp.einsum("nhwc,oh,pw->nopc", a, mh, mw)

    return primitive_call(f, _t(x), name="adaptive_avg_pool2d")


def adaptive_avg_pool1d(x, output_size, name=None):
    o = output_size if isinstance(output_size, int) else output_size[0]

    def f(a):
        n = a.shape[2]
        if n % o == 0:
            k = n // o
            out = jax.lax.reduce_window(a, 0.0, jax.lax.add, (1, 1, k), (1, 1, k),
                                        "VALID")
            return out / k
        m = jnp.asarray(_adaptive_avg_matrix(n, o, a.dtype))
        return jnp.einsum("ncl,ol->nco", a, m)

    return primitive_call(f, _t(x))


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_hw = _pair(output_size)

    def f(a):
        h, w = a.shape[2], a.shape[3]
        oh = h if out_hw[0] is None else out_hw[0]
        ow = w if out_hw[1] is None else out_hw[1]
        if h % oh == 0 and w % ow == 0:
            kh, kw = h // oh, w // ow
            return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max,
                                         (1, 1, kh, kw), (1, 1, kh, kw), "VALID")
        hs, he = _adaptive_bins(h, oh)
        ws, we = _adaptive_bins(w, ow)
        rows = [jnp.stack([jnp.max(a[:, :, hs[i]:he[i], ws[j]:we[j]], axis=(2, 3))
                           for j in range(ow)], axis=-1) for i in range(oh)]
        return jnp.stack(rows, axis=-2)

    return primitive_call(f, _t(x))


# ------------------------------------------------------------------ norm
def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None, name=None):
    ch_axis = 1 if data_format.startswith("NC") else -1
    use_batch_stats = training and not use_global_stats

    def f(a, rm, rv, *wb):
        reduce_axes = tuple(i for i in range(a.ndim) if i != (ch_axis % a.ndim))
        if use_batch_stats:
            mean = jnp.mean(a, axis=reduce_axes)
            var = jnp.var(a, axis=reduce_axes)
        else:
            mean, var = rm, rv
        shape = [1] * a.ndim
        shape[ch_axis % a.ndim] = -1
        out = (a - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        if wb:
            w, b = wb
            out = out * w.reshape(shape) + b.reshape(shape)
        return out

    args = [_t(x), _t(running_mean).detach(), _t(running_var).detach()]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    out = primitive_call(f, *args, name="batch_norm")

    if use_batch_stats and isinstance(running_mean, Tensor):
        # update running stats in-place (buffer semantics, excluded from autograd)
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        reduce_axes = tuple(i for i in range(xv.ndim) if i != (ch_axis % xv.ndim))
        bm = jax.lax.stop_gradient(jnp.mean(xv, axis=reduce_axes))
        bv = jax.lax.stop_gradient(jnp.var(xv, axis=reduce_axes))
        n = float(np.prod([xv.shape[i] for i in reduce_axes])) if not isinstance(
            xv, jax.core.Tracer
        ) else None
        unbiased = bv if n is None or n <= 1 else bv * n / (n - 1)
        running_mean._value = running_mean._value * momentum + bm * (1 - momentum)
        running_var._value = running_var._value * momentum + unbiased * (1 - momentum)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    nd = len(tuple(normalized_shape))

    def f(a, *wb):
        axes = tuple(range(a.ndim - nd, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        if wb:
            w, b = wb
            out = out * w + b
        return out.astype(a.dtype)

    args = [_t(x)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    return primitive_call(f, *args, name="layer_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    def f(a, *wb):
        n, c = a.shape[0], a.shape[1]
        rest = a.shape[2:]
        g = a.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        if wb:
            w, b = wb
            shape = [1, c] + [1] * len(rest)
            out = out * w.reshape(shape) + b.reshape(shape)
        return out

    args = [_t(x)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    return primitive_call(f, *args, name="group_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        if wb:
            w, b = wb
            shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
            out = out * w.reshape(shape) + b.reshape(shape)
        return out

    args = [_t(x)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    return primitive_call(f, *args, name="instance_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def f(a):
        sq = a * a
        half = size // 2
        pad = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        sq_p = jnp.pad(sq, pad)
        acc = sum(sq_p[:, i : i + a.shape[1]] for i in range(size))
        return a / jnp.power(k + alpha * acc / size, beta)

    return primitive_call(f, _t(x))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return primitive_call(
        lambda a: a / jnp.maximum(
            jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True), epsilon
        ),
        _t(x),
    )


# ------------------------------------------------------------------ embedding / dropout
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(idx, w):
        out = jnp.take(w, idx.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    xt, wt = _t(x).detach(), _t(weight)
    from ..core import dispatch as dispatch_mod
    from ..core import tape as tape_mod

    static_build = (dispatch_mod._static_hook is not None
                    and dispatch_mod._static_hook[0]((xt, wt)))
    if (sparse and tape_mod.is_grad_enabled() and not wt.stop_gradient
            and not static_build  # program build records the dense op
            and wt._tape_node is None  # leaf param: the tape can hold a
            #   SelectedRows ct; an op-derived weight's upstream vjp cannot
            and not isinstance(wt._value, jax.core.Tracer)
            and not isinstance(xt._value, jax.core.Tracer)):
        return _sparse_embedding(xt, wt, padding_idx, f)
    return primitive_call(f, xt, wt, name="embedding")


def _sparse_embedding(xt, wt, padding_idx, fwd):
    """Eager embedding whose backward emits a SelectedRows gradient
    (reference: embedding op is_sparse=True -> SelectedRows W@GRAD,
    phi/core/selected_rows.h:1) — the [vocab, hidden] dense grad never
    materializes. Under jit tracing this path is bypassed (XLA scatter-add
    is fused there anyway)."""
    from ..core import tape as tape_mod
    from ..core.selected_rows import SelectedRows

    idx_arr = xt._value
    out_val = fwd(idx_arr, wt._value)
    vocab = int(wt._value.shape[0])

    def vjp_fn(g):
        rows = idx_arr.reshape(-1).astype(jnp.int32)
        vals = g.reshape(-1, g.shape[-1]).astype(wt._value.dtype)
        if padding_idx is not None:
            keep = rows != padding_idx
            vals = jnp.where(keep[:, None], vals, 0.0)
        return ((SelectedRows(rows, vals, vocab),),)

    out = Tensor(out_val, stop_gradient=False)
    node = tape_mod.make_node(
        vjp_fn, [[wt]], [out],
        [jax.ShapeDtypeStruct(out_val.shape, out_val.dtype)],
        is_tuple_out=False, name="embedding_sparse_grad",
    )
    out._tape_node = node
    out._out_index = 0
    return out


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0:
        return _t(x)
    key = next_rng_key()

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return primitive_call(f, _t(x), name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return _t(x)
    key = next_rng_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return primitive_call(f, _t(x))


# ------------------------------------------------------------------ losses
def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    def f(logits, lab, *w):
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30)
        )
        if soft_label:
            loss = -jnp.sum(lab * lp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == lp.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            if label_smoothing > 0.0:
                n = lp.shape[axis]
                onehot = jax.nn.one_hot(lab_i, n, axis=axis, dtype=lp.dtype)
                smooth = onehot * (1 - label_smoothing) + label_smoothing / n
                loss = -jnp.sum(smooth * lp, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lp, jnp.expand_dims(lab_i, axis), axis=axis
                ).squeeze(axis)
            if w:
                wt = jnp.take(w[0], lab_i, axis=0)
                loss = loss * wt
            valid = lab_i != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid), 1)
                if w:
                    denom = jnp.maximum(jnp.sum(jnp.where(valid, wt, 0.0)), 1e-12)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [_t(input), _t(label).detach()]
    if weight is not None:
        args.append(_t(weight))
    return primitive_call(f, *args, name="cross_entropy")


def linear_cross_entropy(hidden, weight, label, transpose_y=False,
                         chunk_size=256, ignore_index=-100, name=None):
    """Fused LM-head projection + softmax cross-entropy, chunked over sequence.

    Computes ``cross_entropy(hidden @ W, label)`` without ever materializing the
    full ``[batch, seq, vocab]`` logits tensor: the sequence axis is scanned in
    chunks, each chunk's logits are produced on the MXU, reduced to (logsumexp,
    target-logit) in fp32, and rematerialized in the backward (`jax.checkpoint`)
    so peak HBM holds one ``[batch, chunk, vocab]`` block instead of the whole
    thing. Reference analog: the fused softmax+CE kernel
    `/root/reference/paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu`
    (which tiles vocab across ranks for the same reason — logits don't fit).

    Args:
        hidden: ``[..., seq, in_features]`` activations (the pre-head trunk).
        weight: ``[in_features, vocab]`` or, with ``transpose_y``, ``[vocab,
            in_features]`` (tied-embedding layout).
        label: integer targets broadcastable to ``hidden.shape[:-1]``.
    Returns mean loss over non-ignored positions (scalar fp32 Tensor).
    """

    def f(h, w, lab):
        lead = h.shape[:-1]
        hidden_dim = h.shape[-1]
        h2 = h.reshape(-1, hidden_dim)
        lab2 = lab.reshape(-1).astype(jnp.int32)
        n = h2.shape[0]
        c = min(chunk_size, n)
        pad = (-n) % c
        if pad:
            h2 = jnp.pad(h2, ((0, pad), (0, 0)))
            lab2 = jnp.pad(lab2, (0, pad), constant_values=ignore_index)
        nchunk = h2.shape[0] // c
        hc = h2.reshape(nchunk, c, hidden_dim)
        lc = lab2.reshape(nchunk, c)

        @jax.checkpoint
        def chunk_stats(h_blk, l_blk):
            # fp32 MXU accumulation (not a post-hoc cast): bf16 inputs keep
            # full-precision partial sums, the standard TPU matmul idiom
            logits = jnp.matmul(h_blk, w.T if transpose_y else w,
                                preferred_element_type=jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            safe = jnp.where(l_blk == ignore_index, 0, l_blk)
            tgt = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
            valid = l_blk != ignore_index
            losses = jnp.where(valid, lse - tgt, 0.0)
            return jnp.sum(losses), jnp.sum(valid, dtype=jnp.float32)

        def body(carry, blk):
            tot, cnt = carry
            s, k = chunk_stats(*blk)
            return (tot + s, cnt + k), None

        (total, count), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (hc, lc)
        )
        return total / jnp.maximum(count, 1.0)

    return primitive_call(f, _t(hidden), _t(weight), _t(label).detach(),
                          name="linear_cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               axis=-1, return_softmax=False, name=None):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis
    )
    loss = loss.unsqueeze(axis) if loss.ndim < _t(logits).ndim else loss
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return primitive_call(
        lambda a, b: _reduce((a - b) ** 2, reduction), _t(input), _t(label), name="mse_loss"
    )


def square_error_cost(input, label):
    return primitive_call(lambda a, b: (a - b) ** 2, _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return primitive_call(
        lambda a, b: _reduce(jnp.abs(a - b), reduction), _t(input), _t(label)
    )


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def f(lp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        loss = -jnp.take_along_axis(lp, lab_i[:, None], axis=1).squeeze(1)
        if w:
            wt = jnp.take(w[0], lab_i, axis=0)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.sum(wt)
        return _reduce(loss, reduction)

    args = [_t(input), _t(label).detach()]
    if weight is not None:
        args.append(_t(weight))
    return primitive_call(f, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *w):
        loss = -(y * jnp.log(jnp.maximum(p, 1e-12)) + (1 - y) * jnp.log(jnp.maximum(1 - p, 1e-12)))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return primitive_call(f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, *rest):
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        i = 0
        if pos_weight is not None:
            pw = rest[i]
            i += 1
            log_w = (pw - 1) * y + 1
            loss = loss * log_w
        if weight is not None:
            loss = loss * rest[i]
        return _reduce(loss, reduction)

    args = [_t(logit), _t(label)]
    if pos_weight is not None:
        args.append(_t(pos_weight))
    if weight is not None:
        args.append(_t(weight))
    return primitive_call(f, *args)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return primitive_call(f, _t(input), _t(label))


def kl_div(input, label, reduction="mean", name=None):
    def f(lp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return primitive_call(f, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return primitive_call(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        _t(input), _t(other), _t(label),
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return primitive_call(
        lambda a, y: _reduce(jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        _t(input), _t(label),
    )


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return primitive_call(
        lambda a, b: jnp.sum(a * b, axis=axis)
        / jnp.maximum(jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis), eps),
        _t(x1), _t(x2),
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(y):
        n = y.shape[-1]
        return y * (1 - epsilon) + epsilon / n

    return primitive_call(f, _t(label))


def one_hot(x, num_classes, name=None):
    return primitive_call(
        lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes), _t(x).detach()
    )


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    from ..core.dtype import to_jax_dtype

    ml = maxlen if maxlen is not None else int(np.asarray(_t(lengths)._value).max())
    return primitive_call(
        lambda l: (jnp.arange(ml)[None, :] < l[:, None]).astype(to_jax_dtype(dtype)),
        _t(lengths).detach(),
    )


# ------------------------------------------------------------------ shape ops
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    def f(a):
        p = list(pad)
        if len(p) == 2 * a.ndim:
            cfg = [(p[2 * i], p[2 * i + 1]) for i in range(a.ndim)]
        else:
            # paddle convention: pad applies to last len(p)//2 spatial dims (reversed pairs)
            n = len(p) // 2
            cfg = [(0, 0)] * (a.ndim - n)
            # NCHW: [l, r, t, b] applies to (W, H) — pairs fill trailing dims from the end
            pairs = [(p[2 * i], p[2 * i + 1]) for i in range(n)]
            cfg += list(reversed(pairs)) if data_format.startswith("NC") else list(reversed(pairs))
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)

    return primitive_call(f, _t(x), name="pad")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    def f(a):
        n, c = a.shape[0], a.shape[1]
        ih, iw = a.shape[2], a.shape[3]
        if size is not None:
            oh, ow = _pair(size)
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else (scale_factor, scale_factor)
            oh, ow = int(ih * sf[0]), int(iw * sf[1])
        method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
                  "linear": "linear", "trilinear": "linear", "area": "linear"}[mode]
        out = jax.image.resize(a, (n, c, oh, ow), method=method)
        return out

    return primitive_call(f, _t(x), name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)

    return primitive_call(f, _t(x))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                patches.append(
                    a[:, :, i * d[0] : i * d[0] + oh * s[0] : s[0],
                      j * d[1] : j * d[1] + ow * s[1] : s[1]]
                )
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * k[0] * k[1], oh * ow)

    return primitive_call(f, _t(x))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def f(a, g):
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners else ((g[..., 0] + 1) * w - 1) / 2
        gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners else ((g[..., 1] + 1) * h - 1) / 2
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1

        def sample(yy, xx):
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yy = jnp.clip(yy, 0, h - 1)
            xx = jnp.clip(xx, 0, w - 1)
            v = a[jnp.arange(n)[:, None, None], :, yy, xx]  # n,oh,ow,c
            return jnp.where(valid[..., None], v, 0.0)

        wx = gx - x0
        wy = gy - y0
        out = (
            sample(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
            + sample(y0, x1) * (wx * (1 - wy))[..., None]
            + sample(y1, x0) * ((1 - wx) * wy)[..., None]
            + sample(y1, x1) * (wx * wy)[..., None]
        )
        return jnp.transpose(out, (0, 3, 1, 2))

    return primitive_call(f, _t(x), _t(grid))


# ------------------------------------------------------------------ attention
def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Fused attention (reference: operators/fused/fused_attention_op.cu).

    Uses the Pallas flash-attention kernel on TPU when enabled; composite XLA
    otherwise (XLA fuses the softmax chain well on its own).
    """
    from ..kernels import attention as _attn

    args = [_t(query), _t(key), _t(value)]
    if attn_mask is not None:
        args.append(_t(attn_mask))

    def f(q, k, v, *m):
        from ..distributed.sequence_parallel import active_sp_axis, ring_attention

        sp = active_sp_axis()
        if sp is not None:
            if m:
                raise NotImplementedError(
                    "explicit attn_mask is not supported under sequence "
                    "parallelism (q/k/v are sequence shards; a local mask "
                    "would silently drop cross-shard attention) — use "
                    "is_causal=True or run without the sp axis"
                )
            # sequence-parallel scope: q/k/v are sequence shards — ring attention
            return ring_attention(q, k, v, sp, causal=is_causal)
        return _attn.sdpa(q, k, v, m[0] if m else None, is_causal=is_causal)

    out = primitive_call(f, *args, name="scaled_dot_product_attention")
    if dropout_p > 0.0 and training:
        out = dropout(out, dropout_p, training=training)
    return out


def gather_tree(ids, parents):
    """Beam-search backtracking (reference op: gather_tree_op.cc); see
    nn/decode.py for the lax.scan implementation."""
    from .decode import gather_tree as _gt

    return _gt(ids, parents)
