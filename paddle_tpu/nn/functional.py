"""nn.functional — neural-net ops lowered to XLA (reference: python/paddle/nn/functional/).

Conv/pool map to lax.conv_general_dilated / reduce_window (MXU + fused by XLA);
attention has a Pallas flash-attention fast path (paddle_tpu/kernels/) gated by
FLAGS_use_pallas_kernels when running on real TPU.
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.rng import next_rng_key
from ..core.tensor import Tensor

__all__ = [
    "relu", "relu6", "gelu", "sigmoid", "tanh", "softmax", "log_softmax",
    "leaky_relu", "elu", "selu", "silu", "swish", "hardswish", "hardsigmoid",
    "hardtanh", "mish", "softplus", "softsign", "tanhshrink", "softshrink",
    "hardshrink", "prelu", "glu", "maxout",
    "linear", "conv1d", "conv2d", "conv3d", "conv2d_transpose",
    "max_pool1d", "max_pool2d", "avg_pool1d", "avg_pool2d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_max_pool2d",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "local_response_norm",
    "embedding", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "cross_entropy", "softmax_with_cross_entropy", "linear_cross_entropy",
    "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_similarity", "normalize", "label_smooth", "one_hot", "pad",
    "interpolate", "upsample", "pixel_shuffle", "unfold", "grid_sample",
    "scaled_dot_product_attention", "sequence_mask", "temperature_scaled_softmax",
    "rrelu", "celu", "logsigmoid", "gumbel_softmax", "square_error_cost",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


# ------------------------------------------------------------------ activations
def relu(x, name=None):
    return primitive_call(jax.nn.relu, _t(x), name="relu")


def relu6(x, name=None):
    return primitive_call(jax.nn.relu6, _t(x), name="relu6")


def gelu(x, approximate=False, name=None):
    return primitive_call(lambda a: jax.nn.gelu(a, approximate=approximate), _t(x), name="gelu", attrs={"approximate": bool(approximate)})


def sigmoid(x, name=None):
    return primitive_call(jax.nn.sigmoid, _t(x), name="sigmoid")


def logsigmoid(x, name=None):
    return primitive_call(jax.nn.log_sigmoid, _t(x), name="logsigmoid")


def tanh(x, name=None):
    return primitive_call(jnp.tanh, _t(x), name="tanh")


def softmax(x, axis=-1, dtype=None, name=None):
    from ..core.dtype import to_jax_dtype

    def f(a):
        if dtype is not None:
            a = a.astype(to_jax_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)

    attrs = {"axis": axis}
    if dtype is not None:
        attrs["cast_dtype"] = str(dtype)  # exporter must not drop the cast
    return primitive_call(f, _t(x), name="softmax", attrs=attrs)


def temperature_scaled_softmax(x, temperature=1.0, axis=-1, name=None):
    return primitive_call(lambda a: jax.nn.softmax(a / temperature, axis=axis), _t(x))


def log_softmax(x, axis=-1, dtype=None, name=None):
    return primitive_call(lambda a: jax.nn.log_softmax(a, axis=axis), _t(x), name="log_softmax")


def leaky_relu(x, negative_slope=0.01, name=None):
    return primitive_call(lambda a: jax.nn.leaky_relu(a, negative_slope), _t(x))


def elu(x, alpha=1.0, name=None):
    return primitive_call(lambda a: jax.nn.elu(a, alpha), _t(x))


def celu(x, alpha=1.0, name=None):
    return primitive_call(lambda a: jax.nn.celu(a, alpha), _t(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return primitive_call(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), _t(x))


def silu(x, name=None):
    return primitive_call(jax.nn.silu, _t(x), name="silu")


def swish(x, name=None):
    return silu(x)


def hardswish(x, name=None):
    return primitive_call(lambda a: a * jnp.clip(a + 3, 0, 6) / 6, _t(x))


def hardsigmoid(x, slope=1 / 6, offset=0.5, name=None):
    return primitive_call(lambda a: jnp.clip(a * slope + offset, 0, 1), _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return primitive_call(lambda a: jnp.clip(a, min, max), _t(x))


def mish(x, name=None):
    return primitive_call(lambda a: a * jnp.tanh(jax.nn.softplus(a)), _t(x))


def softplus(x, beta=1, threshold=20, name=None):
    return primitive_call(
        lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta), _t(x)
    )


def softsign(x, name=None):
    return primitive_call(jax.nn.soft_sign, _t(x))


def tanhshrink(x, name=None):
    return primitive_call(lambda a: a - jnp.tanh(a), _t(x))


def softshrink(x, threshold=0.5, name=None):
    return primitive_call(
        lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)),
        _t(x),
    )


def hardshrink(x, threshold=0.5, name=None):
    return primitive_call(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return primitive_call(f, _t(x), _t(weight), name="prelu")


def rrelu(x, lower=0.125, upper=0.333, training=True, name=None):
    if training:
        a = jax.random.uniform(next_rng_key(), (), float, lower, upper)
    else:
        a = (lower + upper) / 2
    return primitive_call(lambda v: jnp.where(v >= 0, v, a * v), _t(x))


def glu(x, axis=-1, name=None):
    return primitive_call(lambda a: jax.nn.glu(a, axis=axis), _t(x))


def maxout(x, groups, axis=1, name=None):
    def f(a):
        shape = list(a.shape)
        c = shape[axis]
        new_shape = shape[:axis] + [groups, c // groups] + shape[axis + 1 :]
        return jnp.max(a.reshape(new_shape), axis=axis)

    return primitive_call(f, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = jax.random.gumbel(next_rng_key(), tuple(x.shape))

    def f(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            # straight-through: forward value is the one-hot (y - sg(y) == 0),
            # backward sees d(y)/da — the soft distribution's gradient
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return primitive_call(f, _t(x))


# ------------------------------------------------------------------ linear/conv
def linear(x, weight, bias=None, name=None):
    if bias is None:
        return primitive_call(lambda a, w: a @ w, _t(x), _t(weight), name="linear")
    return primitive_call(lambda a, w, b: a @ w + b, _t(x), _t(weight), _t(bias), name="linear")


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        # None entries survive: adaptive pools use None = keep input dim
        return tuple(None if i is None else int(i) for i in v)
    if v is None:
        return (None,) * n
    return (int(v),) * n


def _conv_padding(padding, k, dilation, n):
    """Return lax-style padding config for int / list / SAME / VALID."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad = _conv_padding(padding, None, dilation, 2)
    dn = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")

    def f(a, w, *b):
        if data_format == "NHWC":
            w = jnp.transpose(w, (2, 3, 1, 0))
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=jnp.float32 if a.dtype == jnp.float32 else None,
        )
        if b:
            bias_shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
            out = out + b[0].reshape(bias_shape)
        return out.astype(a.dtype)

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="conv2d", attrs={
        "strides": list(stride), "paddings_raw": padding,
        "dilations": list(dilation), "groups": groups,
        "data_format": data_format})


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    stride = _pair(stride, 1)
    dilation = _pair(dilation, 1)
    pad = _conv_padding(padding, None, dilation, 1)

    def f(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=("NCH", "OIH", "NCH"), feature_group_count=groups,
        )
        if b:
            out = out + b[0].reshape(1, -1, 1)
        return out.astype(a.dtype)

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="conv1d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    pad = _conv_padding(padding, None, dilation, 3)

    def f(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"), feature_group_count=groups,
        )
        if b:
            out = out + b[0].reshape(1, -1, 1, 1, 1)
        return out.astype(a.dtype)

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="conv3d")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCHW", name=None):
    # weight layout IOHW (paddle convention: [in, out/groups, H, W]); shared
    # N-d implementation lives in _conv_transpose_nd below
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, ("NCHW", "OIHW", "NCHW"),
                              "conv2d_transpose")


# ------------------------------------------------------------------ pooling
def _pool(x, kernel, stride, padding, reducer, init, data_format="NCHW", avg=False,
          ceil_mode=False, exclusive=True, nd=2):
    kernel = _pair(kernel, nd)
    stride = _pair(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _pair(padding, nd)
        pad = [(0, 0), (0, 0)] + [(pi, pi) for pi in p]
    spatial_off = 2 if data_format.startswith("NC") else 1
    window = [1] * spatial_off + list(kernel) + ([1] if not data_format.startswith("NC") else [])
    strides = [1] * spatial_off + list(stride) + ([1] if not data_format.startswith("NC") else [])
    if data_format.startswith("NC"):
        window = [1, 1] + list(kernel)
        strides = [1, 1] + list(stride)
    if isinstance(pad, list) and not data_format.startswith("NC"):
        pad = [(0, 0)] + pad[2:] + [(0, 0)]
    if ceil_mode and isinstance(pad, list):
        # extra right/bottom padding so the last partial window is kept:
        # out = ceil((n + 2p - k)/s) + 1 instead of floor (+1)
        shape = tuple(_t(x).shape) if hasattr(x, "shape") else None
        if shape is not None:
            spatial_dims = ([d for d in range(2, 2 + nd)]
                            if data_format.startswith("NC")
                            else [d for d in range(1, 1 + nd)])
            pad = list(pad)
            for i, d in enumerate(spatial_dims):
                n = int(shape[d]) + pad[d][0] + pad[d][1] - kernel[i]
                rem = n % stride[i]
                if rem:
                    pad[d] = (pad[d][0], pad[d][1] + stride[i] - rem)

    def f(a):
        out = jax.lax.reduce_window(a, init, reducer, window, strides, pad)
        if avg:
            if not exclusive:
                # divide by the full kernel size, counting padded zeros
                # (reference: pool_op exclusive=False)
                return out / float(np.prod(kernel))
            if not isinstance(pad, str) and all(p == (0, 0) for p in pad):
                return out / float(np.prod(kernel))
            # exclusive: divide by the number of real (non-pad) elements
            counts = jax.lax.reduce_window(
                jnp.ones_like(a), 0.0, jax.lax.add, window, strides, pad
            )
            return out / counts
        return out

    return primitive_call(f, _t(x), name="pool", attrs={
        "ksize": list(kernel), "strides_attr": list(stride),
        "paddings_raw": padding, "pooling_type": "avg" if avg else "max",
        "ceil_mode": ceil_mode, "exclusive": exclusive,
        "data_format": data_format})


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_nd_with_indices(x, kernel_size, stride, padding, nd=2,
                                         ceil_mode=ceil_mode,
                                         data_format=data_format)
    return _pool(x, kernel_size, stride, padding, jax.lax.max, -jnp.inf, data_format,
                 ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, jax.lax.add, 0.0, data_format,
                 avg=True, ceil_mode=ceil_mode, exclusive=exclusive)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    if return_mask:
        return _max_pool_nd_with_indices(x, kernel_size, stride, padding, nd=1,
                                         ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, jax.lax.max, -jnp.inf, "NCL",
                 ceil_mode=ceil_mode, nd=1)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, jax.lax.add, 0.0, "NCL",
                 avg=True, ceil_mode=ceil_mode, exclusive=exclusive, nd=1)


def _adaptive_bins(n, out):
    """Torch/paddle adaptive bins: bin i = [floor(i*n/out), ceil((i+1)*n/out))."""
    starts = [(i * n) // out for i in range(out)]
    ends = [-(-((i + 1) * n) // out) for i in range(out)]
    return starts, ends


def _adaptive_avg_matrix(n, out, dtype):
    """(out, n) averaging matrix — adaptive pooling as a matmul (MXU-friendly)."""
    m = np.zeros((out, n), np.float64)
    starts, ends = _adaptive_bins(n, out)
    for i, (s, e) in enumerate(zip(starts, ends)):
        m[i, s:e] = 1.0 / (e - s)
    return m.astype(dtype)


def _adaptive_pool2d_array(a, oh, ow, ptype="avg", nchw=True):
    """Shared adaptive-pool lowering on a raw array: exact reduce_window when
    the output divides the input, interpolating-matrix (avg) / bin loop (max)
    otherwise. Used by the eager ops below AND the pdmodel loader
    (inference/pdmodel.py) so the two cannot drift."""
    h, w = (a.shape[2], a.shape[3]) if nchw else (a.shape[1], a.shape[2])
    oh = h if oh is None else oh
    ow = w if ow is None else ow
    if h % oh == 0 and w % ow == 0:
        kh, kw = h // oh, w // ow
        window = (1, 1, kh, kw) if nchw else (1, kh, kw, 1)
        if ptype == "max":
            return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window,
                                         window, "VALID")
        out = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, window,
                                    "VALID")
        return out / (kh * kw)
    if ptype == "avg":
        mh = jnp.asarray(_adaptive_avg_matrix(h, oh, a.dtype))
        mw = jnp.asarray(_adaptive_avg_matrix(w, ow, a.dtype))
        if nchw:
            return jnp.einsum("nchw,oh,pw->ncop", a, mh, mw)
        return jnp.einsum("nhwc,oh,pw->nopc", a, mh, mw)
    hs, he = _adaptive_bins(h, oh)
    ws, we = _adaptive_bins(w, ow)
    if not nchw:
        a = jnp.moveaxis(a, -1, 1)
    rows = [jnp.stack([jnp.max(a[:, :, hs[i]:he[i], ws[j]:we[j]], axis=(2, 3))
                       for j in range(ow)], axis=-1) for i in range(oh)]
    out = jnp.stack(rows, axis=-2)
    return jnp.moveaxis(out, 1, -1) if not nchw else out


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_hw = _pair(output_size)

    def f(a):
        return _adaptive_pool2d_array(a, out_hw[0], out_hw[1], "avg",
                                      nchw=(data_format == "NCHW"))

    return primitive_call(f, _t(x), name="adaptive_avg_pool2d",
                          attrs={"output_size": list(out_hw),
                                 "data_format": data_format})


def adaptive_avg_pool1d(x, output_size, name=None):
    o = output_size if isinstance(output_size, int) else output_size[0]

    def f(a):
        n = a.shape[2]
        if n % o == 0:
            k = n // o
            out = jax.lax.reduce_window(a, 0.0, jax.lax.add, (1, 1, k), (1, 1, k),
                                        "VALID")
            return out / k
        m = jnp.asarray(_adaptive_avg_matrix(n, o, a.dtype))
        return jnp.einsum("ncl,ol->nco", a, m)

    return primitive_call(f, _t(x))


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_hw = _pair(output_size)

    def f(a):
        return _adaptive_pool2d_array(a, out_hw[0], out_hw[1], "max",
                                      nchw=True)

    return primitive_call(f, _t(x), name="adaptive_max_pool2d",
                          attrs={"output_size": list(out_hw),
                                 "data_format": "NCHW"})


# ------------------------------------------------------------------ norm
def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None, name=None):
    ch_axis = 1 if data_format.startswith("NC") else -1
    use_batch_stats = training and not use_global_stats

    def f(a, rm, rv, *wb):
        reduce_axes = tuple(i for i in range(a.ndim) if i != (ch_axis % a.ndim))
        if use_batch_stats:
            mean = jnp.mean(a, axis=reduce_axes)
            var = jnp.var(a, axis=reduce_axes)
        else:
            mean, var = rm, rv
        shape = [1] * a.ndim
        shape[ch_axis % a.ndim] = -1
        out = (a - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        if wb:
            w, b = wb
            out = out * w.reshape(shape) + b.reshape(shape)
        return out

    args = [_t(x), _t(running_mean).detach(), _t(running_var).detach()]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    out = primitive_call(f, *args, name="batch_norm", attrs={
        "epsilon": epsilon, "momentum": momentum,
        "data_layout": data_format, "use_batch_stats": use_batch_stats})

    if use_batch_stats and isinstance(running_mean, Tensor):
        # update running stats in-place (buffer semantics, excluded from autograd)
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        reduce_axes = tuple(i for i in range(xv.ndim) if i != (ch_axis % xv.ndim))
        bm = jax.lax.stop_gradient(jnp.mean(xv, axis=reduce_axes))
        bv = jax.lax.stop_gradient(jnp.var(xv, axis=reduce_axes))
        n = float(np.prod([xv.shape[i] for i in reduce_axes])) if not isinstance(
            xv, jax.core.Tracer
        ) else None
        unbiased = bv if n is None or n <= 1 else bv * n / (n - 1)
        running_mean._value = running_mean._value * momentum + bm * (1 - momentum)
        running_var._value = running_var._value * momentum + unbiased * (1 - momentum)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    nd = len(tuple(normalized_shape))

    def f(a, *wb):
        if nd == 1 and wb:
            # common trailing-dim case: one-pass pallas kernel on TPU
            # (kernels/fused_layernorm.py); None -> the XLA chain below
            from ..kernels.fused_layernorm import maybe_fused_layer_norm

            fused = maybe_fused_layer_norm(a, wb[0], wb[1], epsilon)
            if fused is not None:
                return fused
        axes = tuple(range(a.ndim - nd, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        if wb:
            w, b = wb
            out = out * w + b
        return out.astype(a.dtype)

    args = [_t(x)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    return primitive_call(f, *args, name="layer_norm", attrs={
        "epsilon": epsilon, "norm_nd": nd})


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    def f(a, *wb):
        n, c = a.shape[0], a.shape[1]
        rest = a.shape[2:]
        g = a.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        if wb:
            w, b = wb
            shape = [1, c] + [1] * len(rest)
            out = out * w.reshape(shape) + b.reshape(shape)
        return out

    args = [_t(x)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    return primitive_call(f, *args, name="group_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        if wb:
            w, b = wb
            shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
            out = out * w.reshape(shape) + b.reshape(shape)
        return out

    args = [_t(x)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    return primitive_call(f, *args, name="instance_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def f(a):
        sq = a * a
        half = size // 2
        pad = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        sq_p = jnp.pad(sq, pad)
        acc = sum(sq_p[:, i : i + a.shape[1]] for i in range(size))
        return a / jnp.power(k + alpha * acc / size, beta)

    return primitive_call(f, _t(x))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return primitive_call(
        lambda a: a / jnp.maximum(
            jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True), epsilon
        ),
        _t(x),
    )


# ------------------------------------------------------------------ embedding / dropout
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    # reference semantics (nn/functional/input.py embedding): a negative
    # padding_idx counts from the end of the vocab; -1 internally is the
    # kNoPadding sentinel, so normalize BEFORE recording/masking
    if padding_idx is not None:
        padding_idx = int(padding_idx)
        if padding_idx < 0:
            padding_idx += int(_t(weight).shape[0])

    def f(idx, w):
        out = jnp.take(w, idx.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    xt, wt = _t(x).detach(), _t(weight)
    from ..core import dispatch as dispatch_mod
    from ..core import tape as tape_mod

    static_build = (dispatch_mod._static_hook is not None
                    and dispatch_mod._static_hook[0]((xt, wt)))
    if (sparse and tape_mod.is_grad_enabled() and not wt.stop_gradient
            and not static_build  # program build records the dense op
            and wt._tape_node is None  # leaf param: the tape can hold a
            #   SelectedRows ct; an op-derived weight's upstream vjp cannot
            and not isinstance(wt._value, jax.core.Tracer)
            and not isinstance(xt._value, jax.core.Tracer)):
        return _sparse_embedding(xt, wt, padding_idx, f)
    return primitive_call(f, xt, wt, name="embedding", attrs={
        "padding_idx": -1 if padding_idx is None else int(padding_idx)})


def _sparse_embedding(xt, wt, padding_idx, fwd):
    """Eager embedding whose backward emits a SelectedRows gradient
    (reference: embedding op is_sparse=True -> SelectedRows W@GRAD,
    phi/core/selected_rows.h:1) — the [vocab, hidden] dense grad never
    materializes. Under jit tracing this path is bypassed (XLA scatter-add
    is fused there anyway)."""
    from ..core import tape as tape_mod
    from ..core.selected_rows import SelectedRows

    idx_arr = xt._value
    out_val = fwd(idx_arr, wt._value)
    vocab = int(wt._value.shape[0])

    def vjp_fn(g):
        rows = idx_arr.reshape(-1).astype(jnp.int32)
        vals = g.reshape(-1, g.shape[-1]).astype(wt._value.dtype)
        if padding_idx is not None:
            keep = rows != padding_idx
            vals = jnp.where(keep[:, None], vals, 0.0)
        return ((SelectedRows(rows, vals, vocab),),)

    out = Tensor(out_val, stop_gradient=False)
    node = tape_mod.make_node(
        vjp_fn, [[wt]], [out],
        [jax.ShapeDtypeStruct(out_val.shape, out_val.dtype)],
        is_tuple_out=False, name="embedding_sparse_grad",
    )
    out._tape_node = node
    out._out_index = 0
    return out


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0:
        return _t(x)
    key = next_rng_key()

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return primitive_call(f, _t(x), name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return _t(x)
    key = next_rng_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return primitive_call(f, _t(x))


# ------------------------------------------------------------------ losses
def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    def f(logits, lab, *w):
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30)
        )
        if soft_label:
            loss = -jnp.sum(lab * lp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == lp.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            if label_smoothing > 0.0:
                n = lp.shape[axis]
                onehot = jax.nn.one_hot(lab_i, n, axis=axis, dtype=lp.dtype)
                smooth = onehot * (1 - label_smoothing) + label_smoothing / n
                loss = -jnp.sum(smooth * lp, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lp, jnp.expand_dims(lab_i, axis), axis=axis
                ).squeeze(axis)
            if w:
                wt = jnp.take(w[0], lab_i, axis=0)
                loss = loss * wt
            valid = lab_i != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid), 1)
                if w:
                    denom = jnp.maximum(jnp.sum(jnp.where(valid, wt, 0.0)), 1e-12)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [_t(input), _t(label).detach()]
    if weight is not None:
        args.append(_t(weight))
    return primitive_call(f, *args, name="cross_entropy")


def linear_cross_entropy(hidden, weight, label, transpose_y=False,
                         chunk_size=256, ignore_index=-100, name=None):
    """Fused LM-head projection + softmax cross-entropy, chunked over sequence.

    Computes ``cross_entropy(hidden @ W, label)`` without ever materializing the
    full ``[batch, seq, vocab]`` logits tensor: the sequence axis is scanned in
    chunks, each chunk's logits are produced on the MXU, reduced to (logsumexp,
    target-logit) in fp32, and rematerialized in the backward (`jax.checkpoint`)
    so peak HBM holds one ``[batch, chunk, vocab]`` block instead of the whole
    thing. Reference analog: the fused softmax+CE kernel
    `/root/reference/paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu`
    (which tiles vocab across ranks for the same reason — logits don't fit).

    Args:
        hidden: ``[..., seq, in_features]`` activations (the pre-head trunk).
        weight: ``[in_features, vocab]`` or, with ``transpose_y``, ``[vocab,
            in_features]`` (tied-embedding layout).
        label: integer targets broadcastable to ``hidden.shape[:-1]``.
    Returns mean loss over non-ignored positions (scalar fp32 Tensor).
    """

    def f(h, w, lab):
        lead = h.shape[:-1]
        hidden_dim = h.shape[-1]
        h2 = h.reshape(-1, hidden_dim)
        lab2 = lab.reshape(-1).astype(jnp.int32)
        n = h2.shape[0]
        c = min(chunk_size, n)
        pad = (-n) % c
        if pad:
            h2 = jnp.pad(h2, ((0, pad), (0, 0)))
            lab2 = jnp.pad(lab2, (0, pad), constant_values=ignore_index)
        nchunk = h2.shape[0] // c
        hc = h2.reshape(nchunk, c, hidden_dim)
        lc = lab2.reshape(nchunk, c)

        @jax.checkpoint
        def chunk_stats(h_blk, l_blk):
            # fp32 MXU accumulation (not a post-hoc cast): bf16 inputs keep
            # full-precision partial sums, the standard TPU matmul idiom
            logits = jnp.matmul(h_blk, w.T if transpose_y else w,
                                preferred_element_type=jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            safe = jnp.where(l_blk == ignore_index, 0, l_blk)
            tgt = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
            valid = l_blk != ignore_index
            losses = jnp.where(valid, lse - tgt, 0.0)
            return jnp.sum(losses), jnp.sum(valid, dtype=jnp.float32)

        def body(carry, blk):
            tot, cnt = carry
            s, k = chunk_stats(*blk)
            return (tot + s, cnt + k), None

        (total, count), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (hc, lc)
        )
        return total / jnp.maximum(count, 1.0)

    return primitive_call(f, _t(hidden), _t(weight), _t(label).detach(),
                          name="linear_cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               axis=-1, return_softmax=False, name=None):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis
    )
    loss = loss.unsqueeze(axis) if loss.ndim < _t(logits).ndim else loss
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return primitive_call(
        lambda a, b: _reduce((a - b) ** 2, reduction), _t(input), _t(label), name="mse_loss"
    )


def square_error_cost(input, label):
    return primitive_call(lambda a, b: (a - b) ** 2, _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return primitive_call(
        lambda a, b: _reduce(jnp.abs(a - b), reduction), _t(input), _t(label)
    )


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def f(lp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        loss = -jnp.take_along_axis(lp, lab_i[:, None], axis=1).squeeze(1)
        if w:
            wt = jnp.take(w[0], lab_i, axis=0)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.sum(wt)
        return _reduce(loss, reduction)

    args = [_t(input), _t(label).detach()]
    if weight is not None:
        args.append(_t(weight))
    return primitive_call(f, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *w):
        loss = -(y * jnp.log(jnp.maximum(p, 1e-12)) + (1 - y) * jnp.log(jnp.maximum(1 - p, 1e-12)))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return primitive_call(f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, *rest):
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        i = 0
        if pos_weight is not None:
            pw = rest[i]
            i += 1
            log_w = (pw - 1) * y + 1
            loss = loss * log_w
        if weight is not None:
            loss = loss * rest[i]
        return _reduce(loss, reduction)

    args = [_t(logit), _t(label)]
    if pos_weight is not None:
        args.append(_t(pos_weight))
    if weight is not None:
        args.append(_t(weight))
    return primitive_call(f, *args)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return primitive_call(f, _t(input), _t(label))


def kl_div(input, label, reduction="mean", name=None):
    def f(lp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return primitive_call(f, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return primitive_call(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        _t(input), _t(other), _t(label),
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return primitive_call(
        lambda a, y: _reduce(jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        _t(input), _t(label),
    )


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return primitive_call(
        lambda a, b: jnp.sum(a * b, axis=axis)
        / jnp.maximum(jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis), eps),
        _t(x1), _t(x2),
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(y):
        n = y.shape[-1]
        return y * (1 - epsilon) + epsilon / n

    return primitive_call(f, _t(label))


def one_hot(x, num_classes, name=None):
    return primitive_call(
        lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes), _t(x).detach()
    )


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    from ..core.dtype import to_jax_dtype

    ml = maxlen if maxlen is not None else int(np.asarray(_t(lengths)._value).max())
    return primitive_call(
        lambda l: (jnp.arange(ml)[None, :] < l[:, None]).astype(to_jax_dtype(dtype)),
        _t(lengths).detach(),
    )


# ------------------------------------------------------------------ shape ops
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    def f(a):
        p = list(pad)
        if len(p) == 2 * a.ndim:
            cfg = [(p[2 * i], p[2 * i + 1]) for i in range(a.ndim)]
        else:
            # paddle convention: pad applies to last len(p)//2 spatial dims (reversed pairs)
            n = len(p) // 2
            cfg = [(0, 0)] * (a.ndim - n)
            # NCHW: [l, r, t, b] applies to (W, H) — pairs fill trailing dims from the end
            pairs = [(p[2 * i], p[2 * i + 1]) for i in range(n)]
            cfg += list(reversed(pairs)) if data_format.startswith("NC") else list(reversed(pairs))
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)

    return primitive_call(f, _t(x), name="pad")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    """Reference: nn/functional/common.py interpolate -> phi interp kernels.

    Modes: nearest / linear / bilinear / trilinear / bicubic / area, over
    3-5D inputs, channels-first or channels-last (data_format). Coordinate
    conventions match the reference kernels: nearest uses the asymmetric
    floor(i*in/out) map; linear-family uses half-pixel (align_mode=0,
    default), asymmetric src=i*in/out (align_mode=1), or corner-aligned
    src=i*(in-1)/(out-1) (align_corners=True) via spatial-only
    map_coordinates; 'area' is the adaptive average pool (matrix form for
    non-divisible factors); bicubic rides jax.image.resize (half-pixel)."""
    channels_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")

    if size is None and scale_factor is None:
        raise ValueError(
            "interpolate: one of size or scale_factor must be set "
            "(reference nn/functional/common.py raises the same)")

    def f(a):
        if channels_last:
            a = jnp.moveaxis(a, -1, 1)
        sp = a.ndim - 2
        in_sp = a.shape[2:]
        if size is not None:
            osz = tuple(size) if isinstance(size, (list, tuple)) \
                else (int(size),) * sp
            if len(osz) != sp:
                raise ValueError(
                    f"interpolate: size has {len(osz)} elements but the "
                    f"input has {sp} spatial dims ({data_format})")
            osz = tuple(int(s) for s in osz)
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else (scale_factor,) * sp
            if len(sf) != sp:
                raise ValueError(
                    f"interpolate: scale_factor has {len(sf)} elements but "
                    f"the input has {sp} spatial dims ({data_format})")
            osz = tuple(int(d * s) for d, s in zip(in_sp, sf))
        out = _interp_core(a, osz, in_sp)
        return jnp.moveaxis(out, 1, -1) if channels_last else out

    def _interp_core(a, osz, in_sp):
        if osz == tuple(in_sp):
            return a
        if mode == "area":
            # adaptive average pooling per spatial dim (exact for divisible
            # factors; interpolating matrix otherwise)
            out = a
            for d, (i_n, o_n) in enumerate(zip(in_sp, osz)):
                if i_n == o_n:
                    continue
                m = jnp.asarray(_adaptive_avg_matrix(i_n, o_n, out.dtype))
                out = jnp.moveaxis(
                    jnp.tensordot(out, m, axes=[[2 + d], [1]]), -1, 2 + d)
            return out
        if mode == "nearest":
            # reference convention: src = floor(i*in/out) (align_corners
            # rounds the corner-aligned positions instead)
            out = a
            for d, (i_n, o_n) in enumerate(zip(in_sp, osz)):
                if i_n == o_n:
                    continue
                if align_corners:
                    idx = jnp.round(
                        jnp.linspace(0.0, i_n - 1.0, o_n)).astype(jnp.int32)
                else:
                    idx = jnp.floor(
                        jnp.arange(o_n) * (i_n / o_n)).astype(jnp.int32)
                out = jnp.take(out, idx, axis=2 + d)
            return out
        if mode in ("linear", "bilinear", "trilinear") and (
                align_corners or align_mode == 1):
            from jax.scipy.ndimage import map_coordinates

            def coords(i_n, o_n):
                if align_corners:
                    return jnp.linspace(0.0, i_n - 1.0, o_n)
                # align_mode=1: asymmetric src = i*in/out, clipped
                return jnp.clip(jnp.arange(o_n) * (i_n / o_n), 0, i_n - 1)

            grids = jnp.meshgrid(*[coords(i_n, o_n)
                                   for i_n, o_n in zip(in_sp, osz)],
                                 indexing="ij")
            flat = a.reshape((-1,) + tuple(in_sp))
            out = jax.vmap(
                lambda img: map_coordinates(img, list(grids), order=1))(flat)
            return out.reshape(a.shape[:2] + tuple(osz))
        if align_corners and mode == "bicubic":
            raise NotImplementedError(
                "bicubic with align_corners=True has no exact lowering here "
                "(jax map_coordinates is linear-only); use "
                "align_corners=False or bilinear")
        method = {"bilinear": "linear", "bicubic": "cubic",
                  "linear": "linear", "trilinear": "linear"}[mode]
        return jax.image.resize(a, a.shape[:2] + osz, method=method)

    return primitive_call(f, _t(x), name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)

    return primitive_call(f, _t(x))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                patches.append(
                    a[:, :, i * d[0] : i * d[0] + oh * s[0] : s[0],
                      j * d[1] : j * d[1] + ow * s[1] : s[1]]
                )
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * k[0] * k[1], oh * ow)

    return primitive_call(f, _t(x))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def f(a, g):
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners else ((g[..., 0] + 1) * w - 1) / 2
        gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners else ((g[..., 1] + 1) * h - 1) / 2
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1

        def sample(yy, xx):
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yy = jnp.clip(yy, 0, h - 1)
            xx = jnp.clip(xx, 0, w - 1)
            v = a[jnp.arange(n)[:, None, None], :, yy, xx]  # n,oh,ow,c
            return jnp.where(valid[..., None], v, 0.0)

        wx = gx - x0
        wy = gy - y0
        out = (
            sample(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
            + sample(y0, x1) * (wx * (1 - wy))[..., None]
            + sample(y1, x0) * ((1 - wx) * wy)[..., None]
            + sample(y1, x1) * (wx * wy)[..., None]
        )
        return jnp.transpose(out, (0, 3, 1, 2))

    return primitive_call(f, _t(x), _t(grid))


# ------------------------------------------------------------------ attention
def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Fused attention (reference: operators/fused/fused_attention_op.cu).

    Uses the Pallas flash-attention kernel on TPU when enabled; composite XLA
    otherwise (XLA fuses the softmax chain well on its own).
    """
    from ..kernels import attention as _attn

    args = [_t(query), _t(key), _t(value)]
    if attn_mask is not None:
        args.append(_t(attn_mask))

    def f(q, k, v, *m):
        from ..distributed.sequence_parallel import active_sp_axis, ring_attention

        sp = active_sp_axis()
        if sp is not None:
            if m:
                raise NotImplementedError(
                    "explicit attn_mask is not supported under sequence "
                    "parallelism (q/k/v are sequence shards; a local mask "
                    "would silently drop cross-shard attention) — use "
                    "is_causal=True or run without the sp axis"
                )
            # sequence-parallel scope: q/k/v are sequence shards — ring attention
            return ring_attention(q, k, v, sp, causal=is_causal)
        return _attn.sdpa(q, k, v, m[0] if m else None, is_causal=is_causal)

    out = primitive_call(f, *args, name="scaled_dot_product_attention",
                         attrs={"is_causal": bool(is_causal)})
    if dropout_p > 0.0 and training:
        out = dropout(out, dropout_p, training=training)
    return out


def gather_tree(ids, parents):
    """Beam-search backtracking (reference op: gather_tree_op.cc); see
    nn/decode.py for the lax.scan implementation."""
    from .decode import gather_tree as _gt

    return _gt(ids, parents)


# ===================================================================== parity
# batch (reference: python/paddle/nn/functional/* __all__) — pooling-3d,
# unpooling, shuffles, pads, losses, grids. Same primitive_call conventions.

def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_nd_with_indices(x, kernel_size, stride, padding, nd=3,
                                         ceil_mode=ceil_mode,
                                         data_format=data_format)
    return _pool(x, kernel_size, stride, padding, jax.lax.max, -jnp.inf,
                 "NCDHW", ceil_mode=ceil_mode, nd=3)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, jax.lax.add, 0.0, "NCDHW",
                 avg=True, ceil_mode=ceil_mode, exclusive=exclusive, nd=3)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    out = _pair(output_size, 3)

    def f(a):
        d, h, w = a.shape[2], a.shape[3], a.shape[4]
        od = d if out[0] is None else out[0]
        oh = h if out[1] is None else out[1]
        ow = w if out[2] is None else out[2]
        md = jnp.asarray(_adaptive_avg_matrix(d, od, a.dtype))
        mh = jnp.asarray(_adaptive_avg_matrix(h, oh, a.dtype))
        mw = jnp.asarray(_adaptive_avg_matrix(w, ow, a.dtype))
        return jnp.einsum("ncdhw,od,ph,qw->ncopq", a, md, mh, mw)

    return primitive_call(f, _t(x), name="adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    o = output_size if isinstance(output_size, int) else output_size[0]

    def f(a):
        n = a.shape[2]
        ss, ee = _adaptive_bins(n, o)
        out = jnp.stack([jnp.max(a[:, :, s:e], axis=2)
                         for s, e in zip(ss, ee)], axis=-1)
        if not return_mask:
            return out
        idx = jnp.stack(
            [jnp.argmax(jax.lax.stop_gradient(a[:, :, s:e]), axis=2) + s
             for s, e in zip(ss, ee)], axis=-1).astype(jnp.int32)
        return out, idx

    return primitive_call(f, _t(x), name="adaptive_max_pool1d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _pair(output_size, 3)

    def f(a):
        d, h, w = a.shape[2], a.shape[3], a.shape[4]
        od = d if out[0] is None else out[0]
        oh = h if out[1] is None else out[1]
        ow = w if out[2] is None else out[2]
        ds, de = _adaptive_bins(d, od)
        hs, he = _adaptive_bins(h, oh)
        ws, we = _adaptive_bins(w, ow)
        planes, iplanes = [], []
        for i in range(od):
            rows, irows = [], []
            for j in range(oh):
                cols, icols = [], []
                for k in range(ow):
                    blk = a[:, :, ds[i]:de[i], hs[j]:he[j], ws[k]:we[k]]
                    cols.append(jnp.max(blk, axis=(2, 3, 4)))
                    if return_mask:
                        bd, bh, bw = blk.shape[2:]
                        flat = jax.lax.stop_gradient(blk).reshape(
                            blk.shape[:2] + (-1,))
                        am = jnp.argmax(flat, axis=2)
                        li, rem = am // (bh * bw), am % (bh * bw)
                        lj, lk = rem // bw, rem % bw
                        icols.append(((li + ds[i]) * h + (lj + hs[j])) * w
                                     + lk + ws[k])
                rows.append(jnp.stack(cols, axis=-1))
                if return_mask:
                    irows.append(jnp.stack(icols, axis=-1))
            planes.append(jnp.stack(rows, axis=-2))
            if return_mask:
                iplanes.append(jnp.stack(irows, axis=-2))
        outv = jnp.stack(planes, axis=-3)
        if not return_mask:
            return outv
        return outv, jnp.stack(iplanes, axis=-3).astype(jnp.int32)

    return primitive_call(f, _t(x), name="adaptive_max_pool3d")


def _pool_argmax(a, window, strides, pads):
    """Flat-spatial argmax per pooling window (int32). Gradient-cut with
    stop_gradient: the variadic reduce_window has no JVP rule, so tangents
    must never reach it — gradients flow through the separate
    differentiable max-pool instead."""
    a = jax.lax.stop_gradient(a)
    spatial = a.shape[2:]
    n_sp = int(np.prod(spatial))
    idx = jnp.arange(n_sp).reshape((1, 1) + spatial)
    idx = jnp.broadcast_to(idx, a.shape)

    def red(xp, yp):
        (xv, xi), (yv, yi) = xp, yp
        take_y = yv > xv
        return (jnp.where(take_y, yv, xv), jnp.where(take_y, yi, xi))

    _, oidx = jax.lax.reduce_window(
        (a, idx), (jnp.asarray(-jnp.inf, a.dtype), jnp.asarray(-1)),
        red, window, strides, pads)
    return oidx.astype(jnp.int32)


def _max_pool_nd_with_indices(x, kernel_size, stride, padding, nd,
                              ceil_mode=False, data_format=None):
    """Max pool returning (out, flat spatial indices) — feeds max_unpool."""
    if ceil_mode:
        raise NotImplementedError(
            "return_mask=True with ceil_mode=True is not supported yet")
    if data_format is not None and not data_format.startswith("NC"):
        raise NotImplementedError(
            f"return_mask=True requires channels-first layout, got {data_format}")
    kernel = _pair(kernel_size, nd)
    stride = _pair(stride if stride is not None else kernel_size, nd)
    pad = _conv_padding(padding, None, (1,) * nd, nd)
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)

    def f(a):
        if isinstance(pad, str):
            if pad == "VALID":
                pads = "VALID"
            else:  # SAME: explicit per-dim pads so indices stay consistent
                pads = [(0, 0), (0, 0)]
                for i in range(nd):
                    n = a.shape[2 + i]
                    total = max((-(-n // stride[i]) - 1) * stride[i]
                                + kernel[i] - n, 0)
                    pads.append((total // 2, total - total // 2))
                pads = tuple(pads)
        else:
            pads = tuple([(0, 0), (0, 0)] + pad)
        # differentiable max (reduce_window max has a grad rule); the argmax
        # side is gradient-cut via stop_gradient
        out = jax.lax.reduce_window(a, jnp.asarray(-jnp.inf, a.dtype),
                                    jax.lax.max, window, strides, pads)
        oidx = _pool_argmax(a, window, strides, pads)
        return out, oidx

    return primitive_call(f, _t(x), name="max_pool_with_index")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool_nd(x, indices, kernel_size, stride, padding,
                          output_size, nd=2)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool_nd(x, indices, kernel_size, stride, padding,
                          output_size, nd=1)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool_nd(x, indices, kernel_size, stride, padding,
                          output_size, nd=3)


def _max_unpool_nd(x, indices, kernel_size, stride, padding, output_size, nd):
    """Scatter pooled values back to their argmax positions (reference
    unpool op: zeros elsewhere)."""
    kernel = _pair(kernel_size, nd)
    stride = _pair(stride if stride is not None else kernel_size, nd)
    padv = _pair(padding, nd)

    def f(a, idx):
        in_sp = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(s) for s in (
                output_size[-nd:] if len(output_size) > nd else output_size))
        else:
            out_sp = tuple(
                (in_sp[i] - 1) * stride[i] - 2 * padv[i] + kernel[i]
                for i in range(nd))
        n, c = a.shape[0], a.shape[1]
        n_out = int(np.prod(out_sp))
        flat = jnp.zeros((n, c, n_out), a.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None],
            jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1)
        ].set(a.reshape(n, c, -1))
        return flat.reshape((n, c) + out_sp)

    return primitive_call(f, _t(x), _t(indices), name="max_unpool")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w).swapaxes(1, 2)\
                    .reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups).swapaxes(3, 4)\
                .reshape(n, h, w, c)

    return primitive_call(f, _t(x), name="channel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            return a.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r,
                                                         h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        return a.transpose(0, 1, 3, 5, 2, 4).reshape(n, h // r, w // r,
                                                     c * r * r)

    return primitive_call(f, _t(x), name="pixel_unshuffle")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    p = _pair(padding, 4)  # [left, right, top, bottom]

    def f(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])))
        return jnp.pad(a, ((0, 0), (p[2], p[3]), (p[0], p[1]), (0, 0)))

    return primitive_call(f, _t(x), name="zeropad2d")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (reference fold op): [N, C*kh*kw, L] -> [N, C, H, W] with
    overlapping patches summed."""
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        a = a.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                rows = i * dh + sh * jnp.arange(nh)
                cols = j * dw + sw * jnp.arange(nw)
                out = out.at[:, :, rows[:, None], cols[None, :]].add(
                    a[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]

    return primitive_call(f, _t(x), name="fold")


def thresholded_relu(x, threshold=1.0, name=None):
    return primitive_call(lambda a: jnp.where(a > threshold, a, 0.0), _t(x),
                          name="thresholded_relu")


def log_sigmoid(x, name=None):
    return primitive_call(jax.nn.log_sigmoid, _t(x), name="log_sigmoid")


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        n = a.shape[-1]
        size = n + abs(int(offset))
        out = jnp.zeros(a.shape[:-1] + (size, size), a.dtype)
        i = jnp.arange(n)
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        out = out.at[..., r, c].set(a)
        if (dim1, dim2) not in ((-2, -1), (out.ndim - 2, out.ndim - 1)):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out

    return primitive_call(f, _t(input), name="diag_embed")


def bilinear(x1, x2, weight, bias=None, name=None):
    """b_k = x1^T W_k x2 (reference bilinear_tensor_product op)."""
    def f(a, b, w, *bias_):
        out = jnp.einsum("ni,oij,nj->no", a, w, b)
        if bias_:
            out = out + bias_[0]
        return out

    args = [_t(x1), _t(x2), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="bilinear")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Sampling grid [N, H, W, 2] from affine matrices [N, 2, 3]
    (reference affine_grid op; pairs with grid_sample)."""
    n, _, h, w = [int(s) for s in out_shape]

    def coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    def f(th):
        ys = coords(h)
        xs = coords(w)
        gx, gy = jnp.meshgrid(xs, ys)  # [h, w]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [h*w, 3]
        grid = jnp.einsum("hk,nok->nho", base, th)  # [n, h*w, 2]
        return grid.reshape(n, h, w, 2)

    return primitive_call(f, _t(theta), name="affine_grid")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """TSM channel shift along time (reference temporal_shift_op)."""
    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        back = jnp.concatenate(
            [a[:, 1:, :fold_c], jnp.zeros_like(a[:, :1, :fold_c])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(a[:, :1, fold_c:2 * fold_c]),
             a[:, :-1, fold_c:2 * fold_c]], axis=1)
        keep = a[:, :, 2 * fold_c:]
        return jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)

    return primitive_call(f, _t(x), name="temporal_shift")


# ------------------------------------------------------------------ in-place
def relu_(x, name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(x, relu(x))


def elu_(x, alpha=1.0, name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(x, elu(x, alpha))


def tanh_(x, name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(x, tanh(x))


def softmax_(x, axis=-1, dtype=None, name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(x, softmax(x, axis=axis, dtype=dtype))


# ------------------------------------------------------------------- losses 2
def log_loss(input, label, epsilon=1e-4, name=None):
    """Negative log likelihood of Bernoulli predictions (reference log_loss
    op: -(y log(p+eps) + (1-y) log(1-p+eps)))."""
    return primitive_call(
        lambda p, y: -(y * jnp.log(p + epsilon)
                       + (1.0 - y) * jnp.log(1.0 - p + epsilon)),
        _t(input), _t(label), name="log_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - Dice coefficient (reference dice_loss: class-prob input
    [N, ..., C], integer label [N, ..., 1])."""
    def f(p, y):
        y1 = jax.nn.one_hot(y.squeeze(-1), p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return primitive_call(f, _t(input), _t(label), name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """Improved N-pair loss (reference npair_loss): softmax CE over the
    anchor-positive similarity matrix with same-label soft targets, plus an
    L2 pull on the embeddings."""
    def f(a, p, y):
        batch = a.shape[0]
        sim = a @ p.T  # [B, B]
        same = (y.reshape(-1, 1) == y.reshape(1, -1)).astype(a.dtype)
        targets = same / jnp.sum(same, axis=1, keepdims=True)
        ce = -jnp.mean(jnp.sum(targets * jax.nn.log_softmax(sim, axis=1),
                               axis=1))
        l2 = jnp.sum(a * a) / batch + jnp.sum(p * p) / batch
        return ce + l2_reg * l2 * 0.25

    return primitive_call(f, _t(anchor), _t(positive), _t(labels),
                          name="npair_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                      reduction="sum", name=None):
    """Focal loss on logits (reference sigmoid_focal_loss)."""
    def f(z, y, *norm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if norm:
            loss = loss / norm[0]
        if reduction == "sum":
            return jnp.sum(loss)
        if reduction == "mean":
            return jnp.mean(loss)
        return loss

    args = [_t(logit), _t(label)] + ([_t(normalizer)] if normalizer is not None else [])
    return primitive_call(f, *args, name="sigmoid_focal_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid over the default complete binary tree (reference
    hierarchical_sigmoid op). Internal nodes number num_classes-1; the path
    for class c follows the binary heap encoding of (c + num_classes) from
    the root, matching the reference's default (non-custom-tree) layout."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "custom trees (path_table/path_code) are not supported yet")
    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    # host-precomputed heap paths per class: node ids + left/right codes
    tables = np.zeros((num_classes, depth), np.int32)
    codes = np.full((num_classes, depth), -1, np.int32)  # -1 = unused slot
    for c in range(num_classes):
        node = c + num_classes  # leaf id in the implicit heap
        path = []
        while node > 1:
            path.append((node // 2, node % 2))
            node //= 2
        for d, (nid, code) in enumerate(reversed(path)):
            tables[c, d] = nid - 1  # internal nodes are 1-indexed heap slots
            codes[c, d] = code

    tab = jnp.asarray(tables)
    cod = jnp.asarray(codes)

    def f(x, y, w, *b):
        nodes = tab[y]  # [B, depth]
        code = cod[y]
        wv = w[nodes]  # [B, depth, F]
        logits = jnp.einsum("bdf,bf->bd", wv, x)
        if b:
            logits = logits + b[0][nodes]
        valid = code >= 0
        # BCE with target = code (1 for right branch)
        t = jnp.where(valid, code, 0).astype(x.dtype)
        ce = jnp.maximum(logits, 0) - logits * t + jnp.log1p(
            jnp.exp(-jnp.abs(logits)))
        return jnp.sum(jnp.where(valid, ce, 0.0), axis=1, keepdims=True)

    args = [_t(input), _t(label), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name="hsigmoid_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC forward loss (reference: warpctc op / F.ctc_loss).

    log_probs: [T, B, C] UNNORMALIZED logits or log-softmax (normalized
    internally like the reference's warpctc with norm_by_times=False);
    labels: [B, L] padded with anything past label_lengths.

    TPU-native: the alpha recursion is one lax.scan over time with the
    standard blank-interleaved label row; all batch rows run masked in
    lockstep (static shapes).
    """
    def f(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        lp = jax.nn.log_softmax(lp, axis=-1)
        L = lab.shape[1]
        S = 2 * L + 1
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        # extended label row: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, S), blank, lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        s_idx = jnp.arange(S)
        valid_s = s_idx[None, :] < (2 * lab_len[:, None] + 1)
        # allow the s-2 skip where ext[s] != blank and ext[s] != ext[s-2]
        ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, ext.dtype),
                                  ext[:, :-2]], axis=1)
        can_skip = (ext != blank) & (ext != ext_m2)

        emit0 = jnp.take_along_axis(lp[0], ext, axis=1)  # [B, S]
        alpha0 = jnp.where(s_idx[None, :] < 2, emit0, neg_inf)
        alpha0 = jnp.where(valid_s, alpha0, neg_inf)

        def step(alpha, lp_t):
            a1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a2 = jnp.where(can_skip, a2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            new = jnp.where(valid_s, merged + emit, neg_inf)
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]
        # per-row final alpha at t = input_length - 1
        a_final = alphas[jnp.clip(in_len - 1, 0, T - 1), jnp.arange(B)]  # [B, S]
        end1 = 2 * lab_len  # final blank
        end2 = jnp.maximum(2 * lab_len - 1, 0)  # final label
        ll = jnp.logaddexp(
            jnp.take_along_axis(a_final, end1[:, None], axis=1),
            jnp.where((lab_len > 0)[:, None],
                      jnp.take_along_axis(a_final, end2[:, None], axis=1),
                      neg_inf))[:, 0]
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(loss.dtype), 1.0)
        if reduction == "mean":
            # reference mean: per-sample loss / label_len, then batch mean
            return jnp.mean(loss / jnp.maximum(
                lab_len.astype(loss.dtype), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return primitive_call(f, _t(log_probs), _t(labels), _t(input_lengths),
                          _t(label_lengths), name="ctc_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-style margin softmax (reference margin_cross_entropy op):
    target cosine -> cos(m1*theta + m2) - m3, all scaled by s. Single-shard
    form; under GSPMD the class dim shards like ParallelCrossEntropy."""
    def f(cos, y):
        theta = jnp.arccos(jnp.clip(cos, -1.0, 1.0))
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        one_hot = jax.nn.one_hot(y, cos.shape[-1], dtype=cos.dtype)
        out = scale * jnp.where(one_hot > 0, tgt, cos)
        lse = jax.scipy.special.logsumexp(out, axis=-1)
        tgt_logit = jnp.sum(out * one_hot, axis=-1)
        loss = lse - tgt_logit
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jax.nn.softmax(out, axis=-1)
        return loss

    return primitive_call(f, _t(logits), _t(label), name="margin_cross_entropy")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference class_center_sample op,
    PartialFC). Host-side: the unique-positive set is data-dependent."""
    import numpy as np_

    from ..core.rng import default_generator

    y = np_.asarray(_t(label)._value if hasattr(label, "_value") else label)
    pos = np_.unique(y)
    rest = np_.setdiff1d(np_.arange(num_classes), pos)
    seed = int(np_.asarray(
        jax.random.randint(default_generator().next_key(), (), 0, 2**31 - 1)))
    rng = np_.random.RandomState(seed)
    n_extra = max(int(num_samples) - pos.size, 0)
    extra = rng.choice(rest, size=min(n_extra, rest.size), replace=False) \
        if n_extra else np_.empty((0,), pos.dtype)
    sampled = np_.sort(np_.concatenate([pos, extra]).astype(np_.int64))
    remap = {c: i for i, c in enumerate(sampled.tolist())}
    y_remap = np_.asarray([remap[v] for v in y.tolist()], np_.int64)
    from ..core.tensor import Tensor as _T

    return _T(jnp.asarray(y_remap)), _T(jnp.asarray(sampled))


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention (reference sparse_attention op, CUDA-only).

    TPU fallback: computes dense attention restricted to the CSR pattern —
    numerically identical to the sparse kernel; a Pallas block-sparse kernel
    is the planned fast path (splash attention covers the causal case)."""
    def f(q, k, v, off, cols):
        b, h, s, d = q.shape
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(d, q.dtype))
        # build a dense mask from CSR, resolving each nnz's row against its
        # OWN (batch, head) offset row — patterns may differ per head
        max_nnz = cols.shape[-1]

        def rows_for(off_row):  # [s+1] -> [max_nnz]
            return jnp.searchsorted(off_row, jnp.arange(max_nnz),
                                    side="right") - 1

        row_of_nnz = jax.vmap(jax.vmap(rows_for))(off)  # [b, h, max_nnz]
        mask = jnp.zeros((b, h, s, s), bool)
        mask = mask.at[
            jnp.arange(b)[:, None, None],
            jnp.arange(h)[None, :, None],
            row_of_nnz,
            cols].set(True)
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(mask, probs, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    return primitive_call(f, _t(query), _t(key), _t(value),
                          _t(sparse_csr_offset), _t(sparse_csr_columns),
                          name="sparse_attention")


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, nd, dim_spec, name):
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    out_pad = _pair(output_padding, nd)
    pad_cfg = padding

    def f(a, w, *b):
        ks = w.shape[2:]
        if isinstance(pad_cfg, int):
            pads = [(pad_cfg, pad_cfg)] * nd
        elif isinstance(pad_cfg, str):
            pads = pad_cfg.upper()
        else:
            pads = _conv_padding(pad_cfg, None, dilation, nd)
        if isinstance(pads, list):
            # output_padding extends the high side of the output (reference
            # conv_transpose semantics for reaching odd sizes under stride)
            lax_pads = [
                (dilation[i] * (k - 1) - p[0],
                 dilation[i] * (k - 1) - p[1] + out_pad[i])
                for i, (p, k) in enumerate(zip(pads, ks))
            ]
        else:
            if any(op != 0 for op in out_pad):
                raise NotImplementedError(
                    "output_padding with string padding is not supported")
            lax_pads = pads
        w_t = jnp.swapaxes(w, 0, 1)  # IO... -> OI...
        w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + nd)))
        out = jax.lax.conv_general_dilated(
            a, w_t, window_strides=(1,) * nd, padding=lax_pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dim_spec, feature_group_count=groups,
        )
        if b:
            out = out + b[0].reshape((1, -1) + (1,) * nd)
        return out.astype(a.dtype)

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return primitive_call(f, *args, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, ("NCH", "OIH", "NCH"),
                              "conv1d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, ("NCDHW", "OIDHW", "NCDHW"),
                              "conv3d_transpose")
