"""Layer: the module base class.

Reference analog: `python/paddle/fluid/dygraph/layers.py` (`Layer:84`) — parameter
registry, sublayer tree, state_dict, hooks, train/eval. TPU-native additions:
`functional_state()` / `functional_call()` which expose the layer as a pure
function over a params pytree — the bridge to `jax.jit`/`jax.grad`/`pjit` whole-step
compilation, and per-parameter sharding specs (PartitionSpec) for GSPMD.
"""
from __future__ import annotations

import collections
from typing import Callable, Iterator

import numpy as np

from ..core.dtype import get_default_dtype
from ..core.tensor import Tensor
from ..utils.misc import unique_name
from . import initializer as I


class ParamAttr:
    """reference: python/paddle/fluid/param_attr.py"""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"Invalid ParamAttr spec: {attr!r}")


_LAZY_INIT_DEPTH = 0  # >0 inside paddle.LazyGuard — create meta parameters


class Parameter(Tensor):
    """A trainable Tensor (reference: framework.Parameter)."""

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable)
        self._is_param = True
        self.trainable = trainable
        self.name = name or unique_name.generate("param")


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        self.training = True
        self._dtype = dtype or get_default_dtype()
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower()
        )
        self._parameters: collections.OrderedDict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: collections.OrderedDict[str, Layer] = collections.OrderedDict()
        self._buffers: collections.OrderedDict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_dtype = None  # set by amp O2 decorate

    # ------------------------------------------------------------ parameters
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        # precedence (reference set_global_initializer semantics): an
        # explicit per-param attr wins; the global override beats every
        # layer-builtin default; then the layer default; then the fallback
        init = (attr.initializer or I._global_default(is_bias)
                or default_initializer)
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        shape = tuple(int(s) for s in shape)
        if _LAZY_INIT_DEPTH > 0:
            # LazyGuard: record shape/dtype + initializer, allocate nothing.
            # Every Initializer returns exactly (shape, to_jax_dtype(dtype))
            # — except Assign, whose shape comes from its captured value —
            # so the aval is known without tracing (tracing would thread the
            # global RNG through an eval_shape and leak tracers into it).
            import jax

            from ..core.dtype import to_jax_dtype

            if isinstance(init, I.Assign):
                shape = tuple(np.shape(init.value))
            p = Parameter(jax.ShapeDtypeStruct(shape, to_jax_dtype(dtype)),
                          trainable=attr.trainable, name=attr.name)
            p._lazy_init = (init, shape, dtype)
        else:
            value = init(shape, dtype)
            p = Parameter(value, trainable=attr.trainable, name=attr.name)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = getattr(attr, "need_clip", True)
        return p

    def lazy_materialize(self, sharding_fn=None):
        """Materialize LazyGuard meta parameters (see framework.LazyGuard).

        sharding_fn(name, param) -> jax.sharding.Sharding | None. When a
        sharding is returned the initializer runs as ONE jitted computation
        with that out_sharding, so each device only ever allocates its own
        shard — a 6.7B model initializes across a mesh without any host
        needing the full array.
        """
        import jax

        n = 0
        for name, p in self.named_parameters():
            if p is None or not p.is_meta:
                continue
            init, shape, dtype = p._lazy_init
            sh = sharding_fn(name, p) if sharding_fn is not None else None
            if sh is not None:
                # draw the key eagerly and pin it inside the jit — letting
                # the initializer advance the global generator under trace
                # would store an escaped tracer in it (see core/rng.py)
                from ..core import rng as rng_mod

                key = rng_mod.next_rng_key()

                def _init(key, i=init, s=shape, d=dtype):
                    with rng_mod.trace_rng_scope(key):
                        return i(s, d)

                value = jax.jit(_init, out_shardings=sh)(key)
            else:
                value = init(shape, dtype)
            p._value = value
            p._lazy_init = None
            n += 1
        return n

    def add_parameter(self, name: str, parameter: Parameter | None):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Tensor | None, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------ attr magic
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter) and params is not None:
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer) and layers is not None:
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
            elif buffers is not None and name in buffers:
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{self.__class__.__name__} has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ------------------------------------------------------------ traversal
    def parameters(self, include_sublayers=True) -> list:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for name, sub, pfx in self._walk(prefix, include_sublayers):
            for pname, p in sub._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{pfx}{pname}", p)

    def _walk(self, prefix="", include_sublayers=True):
        yield ("", self, prefix)
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                yield from sub._walk(f"{prefix}{name}.", True)

    def sublayers(self, include_self=False) -> list:
        out = [self] if include_self else []
        for _, sub in self._sub_layers.items():
            if sub is not None:
                out.extend(sub.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield (prefix.rstrip("."), self)
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield from sub.named_sublayers(f"{prefix}{name}.", include_self=True)

    def children(self):
        return iter([l for l in self._sub_layers.values() if l is not None])

    def named_children(self):
        return iter([(n, l) for n, l in self._sub_layers.items() if l is not None])

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, sub, pfx in self._walk(prefix, include_sublayers):
            for bname, b in sub._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{pfx}{bname}", b)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn: Callable):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # ------------------------------------------------------------ state
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in self._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                own[k].set_value(arr.astype(own[k].numpy().dtype, copy=False))
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------ modes
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            from ..core.dtype import to_jax_dtype

            jdt = to_jax_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(jdt)
            for b in self.buffers():
                if b is not None and np.issubdtype(np.asarray(b._value).dtype, np.floating):
                    b._value = b._value.astype(jdt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    # ------------------------------------------------------------ hooks
    def register_forward_pre_hook(self, hook):
        h = _HookHandle(self._forward_pre_hooks, hook)
        return h

    def register_forward_post_hook(self, hook):
        h = _HookHandle(self._forward_post_hooks, hook)
        return h

    # ------------------------------------------------------------ call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # ------------------------------------------------------------ functional bridge
    def functional_state(self):
        """(params, buffers) as flat name->Tensor dicts — the jit/pjit bridge."""
        params = collections.OrderedDict(self.named_parameters())
        buffers = collections.OrderedDict(self.named_buffers())
        return params, buffers

    def functional_call(self, params: dict, buffers: dict, *inputs, **kwargs):
        """Run forward with parameter/buffer values substituted (pure w.r.t. params).

        Values in `params`/`buffers` may be jax arrays or tracers; originals are
        restored afterwards. Buffer updates (e.g. BN running stats) performed during
        the call are captured and returned as the new buffers dict.
        """
        own_p, own_b = self.functional_state()
        saved = {k: t._value for k, t in {**own_p, **own_b}.items() if t is not None}
        saved_sg = {k: t._stop_gradient for k, t in {**own_p, **own_b}.items() if t is not None}
        try:
            for k, v in params.items():
                if k in own_p and own_p[k] is not None:
                    own_p[k]._value = v._value if isinstance(v, Tensor) else v
            for k, v in (buffers or {}).items():
                if k in own_b and own_b[k] is not None:
                    own_b[k]._value = v._value if isinstance(v, Tensor) else v
            out = self(*inputs, **kwargs)
            new_buffers = {k: t._value for k, t in own_b.items() if t is not None}
            return out, new_buffers
        finally:
            for k, t in {**own_p, **own_b}.items():
                if t is not None and k in saved:
                    t._value = saved[k]
                    t._stop_gradient = saved_sg[k]

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def extra_repr(self):
        return ""


class _HookHandle:
    _next_id = 0

    def __init__(self, hooks_dict, hook):
        self._hooks_dict = hooks_dict
        self._id = _HookHandle._next_id
        _HookHandle._next_id += 1
        hooks_dict[self._id] = hook

    def remove(self):
        self._hooks_dict.pop(self._id, None)
