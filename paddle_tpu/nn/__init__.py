"""paddle_tpu.nn — layers (reference: python/paddle/nn/, 25.6k LoC)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .layer import Layer, ParamAttr, Parameter  # noqa: F401
from .container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layers_common import (  # noqa: F401
    AlphaDropout,
    Bilinear,
    CosineSimilarity,
    Dropout,
    Dropout2D,
    Dropout3D,
    Embedding,
    Flatten,
    Identity,
    Linear,
    Pad1D,
    Pad2D,
    Pad3D,
    PixelShuffle,
    Unfold,
    Upsample,
    UpsamplingBilinear2D,
    UpsamplingNearest2D,
)
from .layers_conv import Conv1D, Conv2D, Conv2DTranspose, Conv3D  # noqa: F401
from .layers_norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    SpectralNorm,
    SyncBatchNorm,
)
from .layers_pooling import (  # noqa: F401
    AdaptiveAvgPool1D,
    AdaptiveAvgPool2D,
    AdaptiveMaxPool2D,
    AvgPool1D,
    AvgPool2D,
    MaxPool1D,
    MaxPool2D,
)
from .layers_activation import (  # noqa: F401
    CELU,
    ELU,
    GELU,
    GLU,
    Hardshrink,
    Hardsigmoid,
    Hardswish,
    Hardtanh,
    LeakyReLU,
    LogSigmoid,
    LogSoftmax,
    Maxout,
    Mish,
    PReLU,
    ReLU,
    ReLU6,
    SELU,
    Sigmoid,
    Silu,
    Softmax,
    Softplus,
    Softshrink,
    Softsign,
    Swish,
    Tanh,
    Tanhshrink,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from .rnn import GRU, GRUCell, LSTM, LSTMCell, SimpleRNN  # noqa: F401
from .rnn import RNN, BiRNN, RNNCellBase, SimpleRNNCell  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .layers_extra import (  # noqa: F401
    AdaptiveAvgPool3D,
    AdaptiveMaxPool1D,
    AdaptiveMaxPool3D,
    AvgPool3D,
    ChannelShuffle,
    Conv1DTranspose,
    Conv3DTranspose,
    CTCLoss,
    Fold,
    HSigmoidLoss,
    MaxPool3D,
    MaxUnPool1D,
    MaxUnPool2D,
    MaxUnPool3D,
    PairwiseDistance,
    PixelUnshuffle,
    Softmax2D,
    ThresholdedReLU,
    ZeroPad2D,
)
from .loss import (  # noqa: F401
    BCELoss,
    BCEWithLogitsLoss,
    CrossEntropyLoss,
    HingeEmbeddingLoss,
    KLDivLoss,
    L1Loss,
    MarginRankingLoss,
    MSELoss,
    NLLLoss,
    SmoothL1Loss,
)

from ..utils import clip_grad as _clip_grad_mod  # noqa: E402

ClipGradByGlobalNorm = _clip_grad_mod.ClipGradByGlobalNorm
ClipGradByNorm = _clip_grad_mod.ClipGradByNorm
ClipGradByValue = _clip_grad_mod.ClipGradByValue
