"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from . import functional as F
from . import initializer as I
from .layer import Layer


def _make(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kw = {**defaults}
            keys = list(defaults.keys())
            for i, a in enumerate(args):
                self._kw[keys[i]] = a
            for k, v in kwargs.items():
                if k != "name":
                    self._kw[k] = v

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _make("ReLU", F.relu)
ReLU6 = _make("ReLU6", F.relu6)
GELU = _make("GELU", F.gelu, approximate=False)
Sigmoid = _make("Sigmoid", F.sigmoid)
LogSigmoid = _make("LogSigmoid", F.logsigmoid)
Tanh = _make("Tanh", F.tanh)
Softmax = _make("Softmax", F.softmax, axis=-1)
LogSoftmax = _make("LogSoftmax", F.log_softmax, axis=-1)
LeakyReLU = _make("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _make("ELU", F.elu, alpha=1.0)
CELU = _make("CELU", F.celu, alpha=1.0)
SELU = _make("SELU", F.selu)
Silu = _make("Silu", F.silu)
Swish = _make("Swish", F.swish)
Hardswish = _make("Hardswish", F.hardswish)
Hardsigmoid = _make("Hardsigmoid", F.hardsigmoid)
Hardtanh = _make("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Mish = _make("Mish", F.mish)
Softplus = _make("Softplus", F.softplus, beta=1, threshold=20)
Softsign = _make("Softsign", F.softsign)
Tanhshrink = _make("Tanhshrink", F.tanhshrink)
Softshrink = _make("Softshrink", F.softshrink, threshold=0.5)
Hardshrink = _make("Hardshrink", F.hardshrink, threshold=0.5)
Maxout = _make("Maxout", F.maxout, groups=2, axis=1)
GLU = _make("GLU", F.glu, axis=-1)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr, default_initializer=I.Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
