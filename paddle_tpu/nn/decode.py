"""Seq2seq decoding API: Decoder, BeamSearchDecoder, dynamic_decode.

Reference analog: python/paddle/nn/decode.py (re-exporting
fluid/layers/rnn.py BeamSearchDecoder/dynamic_decode) and the gather_tree op
(paddle/fluid/operators/gather_tree_op.cc). TPU-native redesign: the decode
loop is a `lax.while_loop` over PREALLOCATED [max_step, ...] output buffers
(static shapes; XLA requires them) with an all-finished early exit — not a
dynamic LoDTensorArray. Results are therefore max_step-padded; pair them with
the returned sequence lengths.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode", "gather_tree"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _map_unwrap(tree):
    return jax.tree_util.tree_map(
        _unwrap, tree, is_leaf=lambda x: isinstance(x, Tensor))


def _map_wrap(tree):
    # is_leaf stops tree_map from descending INTO Tensor (a registered pytree
    # node) and double-wrapping its _value
    return jax.tree_util.tree_map(
        lambda x: Tensor(x) if isinstance(x, jnp.ndarray) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


class Decoder:
    """Abstract decoder driven by `dynamic_decode` (reference Decoder API:
    initialize/step/finalize + tracks_own_finished)."""

    def initialize(self, inits):
        """-> (initial_inputs, initial_states, initial_finished)"""
        raise NotImplementedError

    def final_sequence_lengths(self, final_states):
        """Override to supply authoritative per-sequence lengths from decoder
        state (returns None to keep dynamic_decode's loop-level counts)."""
        return None

    def step(self, time, inputs, states, **kwargs):
        """-> (outputs, next_states, next_inputs, finished)"""
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over a step cell (reference BeamSearchDecoder semantics:
    per-beam log-prob accumulation, finished beams extend only with end_token,
    top-k over beam*vocab, parent backtracking via gather_tree).

    cell: callable (inputs [b*beam, ...], states) -> (outputs, next_states)
    embedding_fn: token ids -> cell inputs
    output_fn: cell outputs -> vocab logits (identity if the cell already
    emits logits)
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] by repeating each row."""
        v = _unwrap(x)
        out = jnp.repeat(v, beam_size, axis=0)
        return Tensor(out) if isinstance(x, Tensor) else out

    def _merge(self, x):  # [batch, beam, ...] -> [batch*beam, ...]
        return x.reshape((-1,) + x.shape[2:])

    def _split(self, x, batch):  # [batch*beam, ...] -> [batch, beam, ...]
        return x.reshape((batch, self.beam_size) + x.shape[1:])

    def initialize(self, initial_cell_states):
        states = _map_unwrap(initial_cell_states)
        batch = jax.tree_util.tree_leaves(states)[0].shape[0]
        tiled = jax.tree_util.tree_map(
            lambda s: jnp.repeat(s, self.beam_size, axis=0), states)
        log_probs = jnp.full((batch, self.beam_size), -jnp.inf, jnp.float32)
        log_probs = log_probs.at[:, 0].set(0.0)  # all beams start identical
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int32)
        tokens = jnp.full((batch * self.beam_size,), self.start_token, jnp.int32)
        inputs = self.embedding_fn(Tensor(tokens)) if self.embedding_fn \
            else Tensor(tokens)
        state = {"cell": tiled, "log_probs": log_probs,
                 "finished": finished, "lengths": lengths}
        return inputs, state, finished

    def step(self, time, inputs, states, **kwargs):
        del time
        states = _map_unwrap(states)
        batch = states["log_probs"].shape[0]
        beam = self.beam_size
        cell_out, next_cell = self.cell(inputs, _map_wrap(states["cell"]))
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = _unwrap(cell_out).astype(jnp.float32)  # [batch*beam, vocab]
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits, axis=-1)
        step_lp = self._split(step_lp, batch)  # [batch, beam, vocab]

        # finished beams may only extend with end_token, at no cost — the
        # standard trick that freezes their cumulative score
        eos_only = jnp.full((vocab,), -jnp.inf).at[self.end_token].set(0.0)
        step_lp = jnp.where(states["finished"][..., None], eos_only, step_lp)

        total = states["log_probs"][..., None] + step_lp  # [batch, beam, vocab]
        flat = total.reshape(batch, beam * vocab)
        top_lp, top_idx = jax.lax.top_k(flat, beam)  # [batch, beam]
        parent = (top_idx // vocab).astype(jnp.int32)
        token = (top_idx % vocab).astype(jnp.int32)

        # reorder beam-major state by the chosen parents
        gidx = parent + jnp.arange(batch)[:, None] * beam  # into batch*beam
        next_cell = jax.tree_util.tree_map(
            lambda s: _unwrap(s)[gidx.reshape(-1)], next_cell)
        prev_finished = states["finished"][jnp.arange(batch)[:, None], parent]
        prev_lengths = states["lengths"][jnp.arange(batch)[:, None], parent]
        finished = prev_finished | (token == self.end_token)
        lengths = prev_lengths + (~prev_finished).astype(jnp.int32)

        outputs = {"scores": top_lp, "predicted_ids": token, "parent_ids": parent}
        next_state = {"cell": next_cell, "log_probs": top_lp,
                      "finished": finished, "lengths": lengths}
        next_inputs = self.embedding_fn(Tensor(token.reshape(-1))) \
            if self.embedding_fn else Tensor(token.reshape(-1))
        return outputs, next_state, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrack parent pointers into whole sequences ([T, batch, beam]).

        The output buffers are max_step-preallocated; past the loop's exit
        step they hold zeros, and a parent id of 0 there would collapse every
        beam onto beam 0 during backtracking. Replace parents in the unwritten
        region with the identity so each beam column survives to the written
        steps. The exit step is max(lengths): unfinished beams count every
        executed step, finished ones stopped earlier."""
        parents = outputs["parent_ids"]
        T, batch, beam = parents.shape
        t_exit = jnp.max(_unwrap(sequence_lengths))
        ident = jnp.broadcast_to(
            jnp.arange(beam, dtype=parents.dtype)[None, None, :], parents.shape)
        parents = jnp.where(jnp.arange(T)[:, None, None] < t_exit,
                            parents, ident)
        ids = gather_tree(Tensor(outputs["predicted_ids"]), Tensor(parents))
        return ids, final_states

    def final_sequence_lengths(self, final_states):
        """Beam reordering makes the loop-level counts wrong; the state's
        parent-gathered lengths are authoritative."""
        return final_states["lengths"]

    @property
    def tracks_own_finished(self):
        return True


def gather_tree(ids, parents):
    """Reassemble beam-search sequences from per-step tokens + parent pointers.

    ids, parents: [max_time, batch, beam]. Returns [max_time, batch, beam]
    where column (b, k) is the full history of final beam k. Reference op:
    gather_tree_op.cc (CPU backtracking loop) — here a reverse lax.scan.
    """
    iv, pv = _unwrap(ids), _unwrap(parents)
    T, batch, beam = iv.shape
    binit = jnp.broadcast_to(jnp.arange(beam, dtype=jnp.int32)[None, :],
                             (batch, beam))
    rows = jnp.arange(batch)[:, None]

    def body(beams, t):
        out_t = iv[t][rows, beams]
        # int32 carry regardless of the caller's parent dtype (int64 parents
        # would flip the scan carry dtype mid-loop)
        prev = pv[t][rows, beams].astype(jnp.int32)
        return prev, out_t

    _, rev = jax.lax.scan(body, binit, jnp.arange(T - 1, -1, -1))
    out = rev[::-1]
    return Tensor(out) if isinstance(ids, Tensor) else out


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive `decoder` until every sequence finishes or max_step_num steps.

    Returns (final_outputs, final_states) or (+ sequence_lengths with
    return_length=True). Outputs are [batch, max_step, ...] (time-major with
    output_time_major=True) padded past each sequence's finish — static
    shapes are the XLA contract, so the buffer is always max_step long.
    """
    del is_test
    if max_step_num is None:
        max_step_num = 256
    max_step_num = int(max_step_num)
    if impute_finished and decoder.tracks_own_finished:
        raise ValueError(
            "impute_finished is incompatible with decoders that reorder rows "
            "each step (tracks_own_finished=True, e.g. BeamSearchDecoder): "
            "the [batch, beam] finished mask cannot be aligned with the "
            "decoder's [batch*beam, ...] internal state.")

    inputs, states, finished = decoder.initialize(inits)
    states_j = _map_unwrap(states)
    finished_j = _unwrap(finished)

    # one real step to learn the decoder's output pytree, then preallocate
    out0, states1, inputs1, fin1 = decoder.step(0, inputs, _map_wrap(states_j),
                                                **kwargs)
    out0_j = _map_unwrap(out0)
    bufs = jax.tree_util.tree_map(
        lambda o: jnp.zeros((max_step_num,) + o.shape, o.dtype).at[0].set(o),
        out0_j)
    if decoder.tracks_own_finished:
        finished_j = _unwrap(fin1)
    else:
        finished_j = finished_j | _unwrap(fin1)
    lengths = jnp.where(finished_j, 1, 0).astype(jnp.int32)

    def cond(carry):
        t, _, _, _, finished, _ = carry
        return (t < max_step_num) & ~jnp.all(finished)

    def body(carry):
        t, inputs, states, bufs, finished, lengths = carry
        out, nstates, ninputs, nfin = decoder.step(t, _map_wrap(inputs),
                                                   _map_wrap(states), **kwargs)
        out_j, nstates_j = _map_unwrap(out), _map_unwrap(nstates)
        ninputs_j, nfin_j = _map_unwrap(ninputs), _unwrap(nfin)
        if impute_finished:  # freeze state/outputs of already-finished rows
            nstates_j = jax.tree_util.tree_map(
                lambda new, old: jnp.where(
                    _bcast(finished, new.shape), old, new), nstates_j, states)
            out_j = jax.tree_util.tree_map(
                lambda o: jnp.where(_bcast(finished, o.shape),
                                    jnp.zeros_like(o), o), out_j)
        bufs = jax.tree_util.tree_map(
            lambda b, o: b.at[t].set(o), bufs, out_j)
        if decoder.tracks_own_finished:
            new_finished = nfin_j
        else:
            new_finished = finished | nfin_j
        lengths = jnp.where(finished, lengths, t + 1)
        return (t + 1, ninputs_j, nstates_j, bufs, new_finished, lengths)

    carry = (jnp.asarray(1), _map_unwrap(inputs1), _map_unwrap(states1),
             bufs, finished_j, lengths)
    t, _, states_f, bufs, finished_f, lengths = jax.lax.while_loop(
        cond, body, carry)
    lengths = jnp.where(finished_f, lengths, max_step_num)
    own_lengths = decoder.final_sequence_lengths(states_f)
    if own_lengths is not None:
        lengths = _unwrap(own_lengths)

    outputs, final_states = decoder.finalize(
        bufs, _map_wrap(states_f), Tensor(lengths))
    if not output_time_major:
        outputs = jax.tree_util.tree_map(
            lambda o: Tensor(jnp.moveaxis(_unwrap(o), 0, 1)), outputs,
            is_leaf=lambda x: isinstance(x, (Tensor, jnp.ndarray)))
    outputs = _map_wrap(outputs)
    if return_length:
        return outputs, final_states, Tensor(lengths)
    return outputs, final_states


def _bcast(mask, shape):
    """Broadcast a [batch, ...] bool mask against `shape` by right-padding."""
    m = mask
    while m.ndim < len(shape):
        m = m[..., None]
    return jnp.broadcast_to(m, shape)
