"""nn.utils: weight/spectral norm reparameterizations + parameter flatten
(reference: python/paddle/nn/utils/{weight_norm_hook,spectral_norm_hook,
transform_parameters}.py).

Reparameterizations install a forward-pre-hook that recomputes the layer's
weight from auxiliary parameters each call — the reference's hook design
maps directly onto Layer.register_forward_pre_hook.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]

_WHOLE = object()  # dim=None sentinel (a user's dim=-1 is a REAL axis)


def _norm_except(w, dim):
    import paddle_tpu as paddle

    axes = [i for i in range(len(w.shape)) if i != dim]
    sq = paddle.sum(paddle.multiply(w, w), axis=axes, keepdim=True)
    return paddle.sqrt(sq)


def weight_norm(layer, name="weight", dim=0):
    """reference: weight_norm_hook.py weight_norm — w = g * v / ||v||.
    dim=None means whole-tensor norm; negative dims count from the end."""
    import paddle_tpu as paddle

    w = getattr(layer, name)
    if dim is not None and dim < 0:
        dim += len(w.shape)
    if dim is None:
        dim = _WHOLE  # whole-tensor norm (reference dim=None semantics)
        g0 = paddle.sqrt(paddle.sum(paddle.multiply(w, w)))
    else:
        g0 = _norm_except(w, dim)
    v = paddle.to_tensor(np.asarray(w.numpy()))
    v.stop_gradient = False
    g = paddle.to_tensor(np.asarray(g0.numpy()))
    g.stop_gradient = False
    layer.add_parameter(name + "_v", v)
    layer.add_parameter(name + "_g", g)

    def _compute():
        vv = getattr(layer, name + "_v")
        gg = getattr(layer, name + "_g")
        if dim is _WHOLE:
            nrm = paddle.sqrt(paddle.sum(paddle.multiply(vv, vv)))
        else:
            nrm = _norm_except(vv, dim)
        return paddle.multiply(paddle.divide(vv, nrm), gg)

    def hook(lyr, inputs):
        # plain attribute, not a parameter: the real trainables are v and g
        object.__setattr__(lyr, name, _compute())
        return None

    # the original weight is no longer a parameter of the layer
    if name in layer._parameters:
        del layer._parameters[name]
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_state = (name, dim, handle)
    object.__setattr__(layer, name, _compute())
    return layer


def remove_weight_norm(layer, name="weight"):
    """reference: weight_norm_hook.py remove_weight_norm — bake the current
    w back as a plain parameter and drop v/g."""
    import paddle_tpu as paddle

    state = getattr(layer, "_weight_norm_state", None)
    if state is None:
        raise ValueError(f"weight_norm was not applied to {layer!r}")
    _, dim, handle = state
    handle.remove()
    v = getattr(layer, name + "_v")
    g = getattr(layer, name + "_g")
    if dim is _WHOLE:
        nrm = paddle.sqrt(paddle.sum(paddle.multiply(v, v)))
    else:
        nrm = _norm_except(v, dim)
    w = paddle.multiply(paddle.divide(v, nrm), g)
    del layer._parameters[name + "_v"]
    del layer._parameters[name + "_g"]
    # drop the hook-era instance attribute or it would SHADOW the restored
    # parameter in Layer.__getattr__ (stale weight, silent no-training)
    layer.__dict__.pop(name, None)
    wp = paddle.to_tensor(np.asarray(w.numpy()))
    wp.stop_gradient = False
    layer.add_parameter(name, wp)
    del layer._weight_norm_state
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """reference: spectral_norm_hook.py spectral_norm — w / sigma_max via
    power iteration, recomputed each forward."""
    import paddle_tpu as paddle

    from ..fluid.layers import spectral_norm as _sn

    if dim is None:
        dim = 0

    orig = getattr(layer, name)
    v = paddle.to_tensor(np.asarray(orig.numpy()))
    v.stop_gradient = False
    layer.add_parameter(name + "_orig", v)
    if name in layer._parameters:
        del layer._parameters[name]

    def hook(lyr, inputs):
        object.__setattr__(lyr, name, _sn(
            getattr(lyr, name + "_orig"), dim=dim,
            power_iters=n_power_iterations, eps=eps))
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_state = (name, handle)
    object.__setattr__(layer, name, _sn(v, dim=dim,
                                        power_iters=n_power_iterations,
                                        eps=eps))
    return layer


def parameters_to_vector(parameters, name=None):
    """reference: transform_parameters.py — concat flattened params."""
    import paddle_tpu as paddle

    return paddle.concat([paddle.reshape(p, [-1]) for p in parameters],
                         axis=0)


def vector_to_parameters(vec, parameters, name=None):
    """reference: transform_parameters.py — scatter a flat vector back."""
    import paddle_tpu as paddle

    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        chunk = paddle.reshape(
            paddle.slice(vec, [0], [offset], [offset + n]), list(p.shape))
        p._value = chunk._value.astype(p._value.dtype)
        offset += n
    return parameters
