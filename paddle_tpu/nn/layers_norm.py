"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0)
            )
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0),
            )
        from ..core.dtype import to_jax_dtype
        from ..core import get_default_dtype

        stat_dt = to_jax_dtype(get_default_dtype())
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), stat_dt)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), stat_dt)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Old-style fluid BatchNorm (acts like BatchNorm2D)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05, **kw):
        super().__init__(num_channels, momentum, epsilon)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch-norm stats sync across the dp axis happens inside pjit via
    GSPMD when the batch dim is sharded — so SyncBatchNorm == BatchNorm here
    (reference: nn/layer/norm.py SyncBatchNorm + NCCL allreduce of stats)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0),
            )

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={list(self._normalized_shape)}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True, default_initializer=I.Constant(0.0)
        )

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias,
                            self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0)
            )
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0),
            )

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias, eps=self._epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):  # rarely used; power-iteration on weight
    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12, name=None):
        super().__init__()
        self.axis, self.power_iters, self.epsilon = axis, power_iters, epsilon

    def forward(self, weight):
        import jax

        w = weight._value if isinstance(weight, Tensor) else weight
        mat = jnp.moveaxis(w, self.axis, 0).reshape(w.shape[self.axis], -1)
        u = jnp.ones((mat.shape[0],), mat.dtype)
        for _ in range(max(1, self.power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.epsilon)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.epsilon)
        sigma = u @ mat @ v
        return Tensor(w / sigma)
