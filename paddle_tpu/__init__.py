"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas re-design with the full capability surface of the
reference framework (PaddlePaddle 2.3, Graphcore-IPU fork): eager + static graph,
hybrid-parallel distributed training (dp / mp / pp / sharding / moe / sp), AMP,
high-level Model API, and an inference path — all lowering to single XLA
computations per step (the whole-graph compile model the reference uses for IPU,
reference: paddle/fluid/platform/device/ipu/).
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

import jax as _jax

# paddle dtype parity: int64 default for ints, float64 representable
_jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS=cpu through the config API as well: the env var alone
# can lose to an eagerly-registered accelerator plugin (the axon TPU tunnel
# blocks backend discovery when its endpoint is down — worker subprocesses
# must never hang on it when the caller asked for CPU).
if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    _jax.config.update("jax_platforms", "cpu")

# ---- core
from .core import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Generator,
    Place,
    TPUPlace,
    Tensor,
    device_count,
    get_default_dtype,
    get_device,
    no_grad,
    enable_grad,
    seed,
    set_default_dtype,
    set_device,
    to_tensor,
)
from .core.tape import is_grad_enabled  # noqa: F401
from .core import memory  # noqa: F401 (allocator stats/flags surface)
from .core.ragged import (  # noqa: F401
    LoDTensor,
    RaggedTensor,
    create_lod_tensor,
)

# ---- functional op surface (paddle.* functions)
from .tensor_ops import *  # noqa: F401,F403
from .tensor_ops import methods as _methods

_methods.install()

from .tensor_ops import creation as _creation  # noqa: E402
from .tensor_ops import math as _math  # noqa: E402

# modules (populated lazily below to avoid import cycles)
from . import nn  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from . import static  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import serving  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from . import inference  # noqa: E402,F401
from . import fft  # noqa: E402,F401
from . import signal  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
from . import device  # noqa: E402,F401
from . import onnx  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from . import compat  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401
from . import callbacks  # noqa: E402,F401
from . import hub  # noqa: E402,F401
from . import reader  # noqa: E402,F401
from . import dataset  # noqa: E402,F401
from . import cost_model  # noqa: E402,F401
from . import _C_ops  # noqa: E402,F401

from .framework.io import load, save  # noqa: E402,F401
from .framework import grad, in_dynamic_mode, LazyGuard  # noqa: E402,F401
from .hapi import Model, summary  # noqa: E402,F401
from .nn.layer import ParamAttr  # noqa: E402,F401
from .batch import batch  # noqa: E402,F401

# paddle.disable_static/enable_static
from .static.mode import disable_static, enable_static, in_static_mode  # noqa: E402,F401


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_grad_enabled_():  # pragma: no cover - alias
    return is_grad_enabled()


def set_grad_enabled(flag: bool):
    from .core import tape

    class _Ctx:
        def __init__(self):
            self._prev = tape.is_grad_enabled()
            tape._set_grad_enabled(flag)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            tape._set_grad_enabled(self._prev)

    return _Ctx()


def get_flags(flags=None):
    from .utils import flags as _flags

    return _flags.get_flags(flags)


def set_flags(flags):
    from .utils import flags as _flags

    return _flags.set_flags(flags)


# ---- parity batch (reference root __all__: python/paddle/__init__.py) ----
# dtype aliases: canonical dtype strings (Tensor.dtype returns these, so
# `x.dtype == paddle.float32` compares equal)
bool = "bool"  # noqa: A001 — parity with paddle.bool shadowing builtins
uint8 = "uint8"
int8 = "int8"
int16 = "int16"
int32 = "int32"
int64 = "int64"
float16 = "float16"
bfloat16 = "bfloat16"
float32 = "float32"
float64 = "float64"
complex64 = "complex64"
complex128 = "complex128"
dtype = str  # dtypes are canonical strings in this framework

from .core.place import CUDAPinnedPlace, NPUPlace  # noqa: E402,F401
from .distributed.parallel import DataParallel  # noqa: E402,F401
from .tensor_ops.math import bincount  # noqa: E402,F401
from .hapi.dynamic_flops import flops  # noqa: E402,F401


def shape(input):
    """Runtime shape as an int32 Tensor (reference: fluid.layers.shape)."""
    import jax.numpy as _jnp

    v = input._value if isinstance(input, Tensor) else _jnp.asarray(input)
    return Tensor(_jnp.asarray(v.shape, _jnp.int32))


def check_shape(shape):  # noqa: A002 — parity signature
    """Validate a shape argument (reference: fluid/layers/utils.py:376)."""
    if isinstance(shape, Tensor):
        if shape.dtype not in ("int32", "int64"):
            raise TypeError(f"shape tensor must be int32/int64, got {shape.dtype}")
        return
    for ele in shape:
        if isinstance(ele, Tensor):
            continue
        if not isinstance(ele, int):
            raise TypeError("All elements in `shape` must be integers")
        if ele < 0:
            raise ValueError("All elements in `shape` must be positive")


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Numpy-backed print options (Tensors repr through numpy)."""
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def disable_signal_handler():
    """Parity no-op: the reference unhooks its C++ fault handlers; this
    runtime installs none."""


def get_cuda_rng_state():
    """Accelerator RNG state (maps to the global threefry key on TPU)."""
    from .core import rng as _rng

    return [_rng.default_generator().get_state()]


def set_cuda_rng_state(state):
    from .core import rng as _rng

    _rng.default_generator().set_state(
        state[0] if isinstance(state, (list, tuple)) else state)
