"""Top-level hub namespace (reference: python/paddle/hub.py:15-21)."""
from .hapi.hub import help, list, load  # noqa: F401

__all__ = ["list", "help", "load"]
