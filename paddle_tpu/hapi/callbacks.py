"""Callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda logs=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda logs=None: None)(logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda step, logs=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda step, logs=None: None)(step, logs)

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def on_begin(self, mode, logs=None):
        self._call("on_begin", mode, logs)

    def on_end(self, mode, logs=None):
        self._call("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call("on_batch_begin", mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call("on_batch_end", mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.time()
        if self.verbose:
            total = self.params.get("epochs")
            print(f"Epoch {epoch + 1}/{total}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = [f"{k}: {_fmt(v)}" for k, v in (logs or {}).items()
                     if k not in ("step", "batch_size")]
            print(f"step {step}: " + ", ".join(items))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self.t0
            items = [f"{k}: {_fmt(v)}" for k, v in (logs or {}).items()
                     if k not in ("step", "batch_size")]
            print(f"Epoch {epoch + 1} done in {dt:.1f}s: " + ", ".join(items))


def _fmt(v):
    if isinstance(v, numbers.Number):
        return f"{v:.4f}"
    if isinstance(v, list):
        return "[" + ", ".join(_fmt(x) for x in v) + "]"
    return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda a, b: a > b + self.min_delta
        else:
            self.better = lambda a, b: a < b - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        v = (logs or {}).get(self.monitor)
        if v is None:
            return
        if isinstance(v, list):
            v = v[0]
        if self.best is None or self.better(v, self.best):
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_train_batch_end(self, step, logs=None):
        sched = getattr(self.model._optimizer, "_lr_scheduler", None)
        if self.by_step and sched is not None:
            sched.step()


class VisualDL(Callback):
    """Metric logging to a JSONL file (VisualDL itself is not in this image)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._fh = None

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._fh = open(os.path.join(self.log_dir, "metrics.jsonl"), "a")

    def on_epoch_end(self, epoch, logs=None):
        if self._fh:
            import json

            rec = {"epoch": epoch}
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number):
                    rec[k] = v
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()

    def on_train_end(self, logs=None):
        if self._fh:
            self._fh.close()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or ["loss"],
    })
    return cl


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer lr when a monitored metric stops improving
    (reference hapi/callbacks.py ReduceLROnPlateau — the callback form of
    optimizer.lr.ReduceOnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = int(patience)
        self.verbose = verbose
        self.min_delta = float(min_delta)
        self.cooldown = int(cooldown)
        self.min_lr = float(min_lr)
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self._cmp = lambda cur, best: cur < best - self.min_delta
            self._best = float("inf")
        else:
            self._cmp = lambda cur, best: cur > best + self.min_delta
            self._best = -float("inf")
        self._wait = 0
        self._cooldown_left = 0

    def _get_metric(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return None if v is None else float(v)

    def on_eval_end(self, logs=None):
        self._step(logs)

    def on_epoch_end(self, epoch, logs=None):
        self._step(logs)

    def _step(self, logs):
        cur = self._get_metric(logs)
        if cur is None:
            return
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self._wait = 0
            return  # hold: no comparisons while cooling down
        if self._cmp(cur, self._best):
            self._best = cur
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            lr = opt.get_lr()
            new_lr = max(lr * self.factor, self.min_lr)
            if new_lr < lr:
                try:
                    opt.set_lr(new_lr)
                except RuntimeError:
                    # LRScheduler-driven optimizer: scale the schedule's base
                    # and refresh its cached last_lr at the current epoch
                    sched = opt._learning_rate
                    if hasattr(sched, "base_lr"):
                        # scale by the clamped ratio so min_lr is honored
                        sched.base_lr *= new_lr / lr
                        sched.step(sched.last_epoch)
                    else:  # pragma: no cover - schedulers all carry base_lr
                        raise
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {lr:.3g} -> {new_lr:.3g}")
            self._wait = 0
            self._cooldown_left = self.cooldown
