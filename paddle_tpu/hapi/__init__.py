from .model import Model, summary  # noqa: F401
from . import callbacks  # noqa: F401
from . import hub  # noqa: F401
from .static_flops import static_flops  # noqa: F401
