"""High-level Model API.

Reference analog: `python/paddle/hapi/model.py:916` (fit:1566,
DynamicGraphAdapter:667). TPU-native difference: `prepare()` builds ONE jitted
train step — forward + loss + backward + optimizer fused into a single XLA
computation with donated param/opt-state buffers (the IPU whole-graph model,
survey §3.5) — instead of per-op dygraph dispatch.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as rng_mod
from ..core import tape as tape_mod
from ..core.tensor import Tensor
from ..io.dataloader import DataLoader
from ..metric import Metric
from . import callbacks as cbs_mod


import contextlib


@contextlib.contextmanager
def _dygraph_scope():
    """Static-mode adapter (reference: hapi/model.py StaticGraphAdapter,
    :248): the reference keeps two engines, so Model dispatches per mode.
    This runtime has ONE engine — the whole-step jit below is already the
    compiled single-computation execution the static adapter exists to
    provide — so under paddle.enable_static() the Model simply suspends op
    recording for its internals; semantics and performance match the
    dygraph path exactly."""
    import paddle_tpu as paddle

    was_static = paddle.in_static_mode()
    if was_static:
        paddle.disable_static()
    try:
        yield
    finally:
        if was_static:
            paddle.enable_static()


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _as_list(inputs)
        self._labels = _as_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self._eval_fn = None
        self._fstate = None  # (params, buffers, opt_state) array pytrees
        self._amp_level = "O0"
        self.stop_training = False

    # --------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _as_list(metrics)
        for m in self._metrics:
            assert isinstance(m, Metric), "metrics must be paddle.metric.Metric"
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
        elif amp_configs is not None:
            self._amp_level = "O1"
        self._build_steps()
        return self

    def _sync_fstate_from_network(self):
        params, buffers = self.network.functional_state()
        p = {k: v._value for k, v in params.items() if v is not None and not v.stop_gradient}
        frozen = {k: v._value for k, v in params.items() if v is not None and v.stop_gradient}
        b = {k: v._value for k, v in buffers.items() if v is not None}
        return p, frozen, b

    def _writeback(self, new_p, new_b):
        params, buffers = self.network.functional_state()
        for k, v in new_p.items():
            params[k]._value = v
        for k, v in new_b.items():
            if k in buffers and buffers[k] is not None:
                buffers[k]._value = v

    def _build_steps(self):
        net = self.network
        loss_obj = self._loss
        opt = self._optimizer
        amp_level = self._amp_level

        def forward_loss(pvals, frozen, bvals, key, inputs, labels, training):
            """Pure: returns (loss_scalar, (outputs, new_buffers))."""
            net.training = training
            if training:
                for l in net.sublayers(include_self=True):
                    l.training = True
            else:
                for l in net.sublayers(include_self=True):
                    l.training = False
            all_p = {**pvals, **frozen}
            with tape_mod.no_grad(), rng_mod.trace_rng_scope(key):
                ctx = _amp_ctx(amp_level)
                with ctx:
                    out, new_b = net.functional_call(
                        all_p, bvals, *[Tensor(x) for x in inputs]
                    )
                outs = out if isinstance(out, (tuple, list)) else [out]
                if loss_obj is not None:
                    label_ts = [Tensor(x) for x in labels]
                    lv = loss_obj(*(list(outs) + label_ts))
                    if isinstance(lv, (list, tuple)):
                        total = lv[0]
                        for extra in lv[1:]:
                            total = total + extra
                        lv = total
                    loss_val = lv._value
                    if loss_val.ndim > 0:
                        loss_val = jnp.mean(loss_val)
                else:
                    loss_val = jnp.zeros((), jnp.float32)
            out_arrays = [o._value if isinstance(o, Tensor) else o for o in outs]
            return loss_val.astype(jnp.float32), (out_arrays, new_b)

        @jax.jit
        def train_step(pvals, frozen, bvals, opt_state, key, lr, inputs, labels):
            (loss, (outs, new_b)), grads = jax.value_and_grad(
                forward_loss, argnums=0, has_aux=True
            )(pvals, frozen, bvals, key, inputs, labels, True)
            new_p, new_opt = opt.functional_update(pvals, grads, opt_state, lr)
            return loss, outs, new_b, new_p, new_opt

        @jax.jit
        def eval_step(pvals, frozen, bvals, key, inputs, labels):
            loss, (outs, new_b) = forward_loss(pvals, frozen, bvals, key, inputs, labels, False)
            return loss, outs

        self._train_step_fn = train_step if opt is not None else None
        self._eval_step_fn = eval_step

    # --------------------------------------------------------------- batches
    def _split_batch(self, data):
        data = list(data) if isinstance(data, (list, tuple)) else [data]
        arrays = [d._value if isinstance(d, Tensor) else jnp.asarray(np.asarray(d)) for d in data]
        if self._labels:
            ni = len(self._inputs) or (len(arrays) - len(self._labels))
        else:
            ni = len(self._inputs) or max(1, len(arrays) - 1)
        return tuple(arrays[:ni]), tuple(arrays[ni:])

    def train_batch(self, inputs, labels=None, update=True):
        with _dygraph_scope():
            return self._train_batch_impl(inputs, labels, update)

    def _train_batch_impl(self, inputs, labels=None, update=True):
        if self._fstate is None:
            p, frozen, b = self._sync_fstate_from_network()
            self._fstate = {
                "p": p, "frozen": frozen, "b": b,
                "opt": self._optimizer.functional_init(p),
            }
        ins = tuple(x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
                    for x in _as_list(inputs))
        lbs = tuple(x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
                    for x in _as_list(labels))
        st = self._fstate
        key = rng_mod.next_rng_key()
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        loss, outs, new_b, new_p, new_opt = self._train_step_fn(
            st["p"], st["frozen"], st["b"], st["opt"], key, lr, ins, lbs
        )
        st["p"], st["b"], st["opt"] = new_p, new_b, new_opt
        self._writeback(new_p, new_b)
        metrics = self._update_metrics(outs, lbs)
        if self._optimizer._lr_scheduler is not None:
            pass  # stepped per-epoch in fit(); manual users call .step()
        return [float(loss)] + metrics if metrics else [float(loss)]

    def eval_batch(self, inputs, labels=None):
        with _dygraph_scope():
            return self._eval_batch_impl(inputs, labels)

    def _eval_batch_impl(self, inputs, labels=None):
        if self._fstate is None:
            p, frozen, b = self._sync_fstate_from_network()
            self._fstate = {"p": p, "frozen": frozen, "b": b,
                            "opt": self._optimizer.functional_init(p) if self._optimizer else None}
        ins = tuple(x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
                    for x in _as_list(inputs))
        lbs = tuple(x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
                    for x in _as_list(labels))
        st = self._fstate
        loss, outs = self._eval_step_fn(st["p"], st["frozen"], st["b"],
                                        rng_mod.next_rng_key(), ins, lbs)
        metrics = self._update_metrics(outs, lbs)
        return [float(loss)] + metrics if metrics else [float(loss)]

    def predict_batch(self, inputs):
        with _dygraph_scope():
            return self._predict_batch_impl(inputs)

    def _predict_batch_impl(self, inputs):
        self.network.eval()
        with tape_mod.no_grad():
            outs = self.network(*[Tensor(np.asarray(x)) if not isinstance(x, Tensor) else x
                                  for x in _as_list(inputs)])
        self.network.train()
        return outs

    def _update_metrics(self, outs, labels):
        vals = []
        for m in self._metrics:
            pred = Tensor(outs[0])
            lab = Tensor(labels[0]) if labels else None
            res = m.compute(pred, lab)
            v = m.update(res if isinstance(res, Tensor) else res[0])
            vals.append(v)
        return vals

    # --------------------------------------------------------------- fit
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        train_loader = self._to_loader(train_data, batch_size, shuffle, drop_last, num_workers)
        eval_loader = self._to_loader(eval_data, batch_size, False, False, num_workers)

        cbks = cbs_mod.config_callbacks(
            callbacks, model=self, epochs=epochs, steps=_safe_len(train_loader),
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose, metrics=["loss"] + self._metrics_names(),
        )
        cbks.on_begin("train")
        self.stop_training = False
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            logs = self._run_one_epoch(train_loader, cbks, "train", num_iters)
            if self._optimizer is not None and self._optimizer._lr_scheduler is not None:
                self._optimizer._lr_scheduler.step()
            if eval_loader is not None and (epoch % eval_freq == 0 or epoch == epochs - 1):
                eval_logs = self.evaluate(eval_loader, verbose=0, _invoke_cbks=False)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            # epoch-end fires AFTER eval so monitors (EarlyStopping,
            # ReduceLROnPlateau) can read eval_* metrics
            cbks.on_epoch_end(epoch, logs)
        cbks.on_end("train", logs)
        return self

    def _run_one_epoch(self, loader, cbks, mode, num_iters=None):
        logs = {}
        for m in self._metrics:
            m.reset()
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            cbks.on_batch_begin(mode, step, logs)
            ins, lbs = self._split_batch(batch)
            if mode == "train":
                res = self.train_batch(ins, lbs)
            else:
                res = self.eval_batch(ins, lbs)
            logs["loss"] = res[0]
            logs["step"] = step
            logs["batch_size"] = ins[0].shape[0] if ins else 1
            for name, m in zip(self._metrics_names(), self._metrics):
                logs[name] = m.accumulate()
            cbks.on_batch_end(mode, step, logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None, _invoke_cbks=True):
        loader = self._to_loader(eval_data, batch_size, False, False, num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            ins, lbs = self._split_batch(batch)
            res = self.eval_batch(ins, lbs)
            losses.append(res[0])
        logs = {"loss": float(np.mean(losses)) if losses else 0.0}
        for name, m in zip(self._metrics_names(), self._metrics):
            logs[name] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._to_loader(test_data, batch_size, False, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            out = self.predict_batch([Tensor(x) for x in ins])
            outs = out if isinstance(out, (list, tuple)) else [out]
            outputs.append([o.numpy() for o in outs])
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([b[i] for b in outputs]) for i in range(n_out)]
        return outputs

    def _to_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if hasattr(data, "__getitem__") or hasattr(data, "__iter__"):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data

    def _metrics_names(self):
        names = []
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    # --------------------------------------------------------------- io
    def save(self, path, training=True):
        from ..framework.io import save as _save

        self._flush_to_network()
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load

        sd = _load(path + ".pdparams")
        self.network.set_state_dict(sd)
        self._fstate = None
        import os

        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    def _flush_to_network(self):
        if self._fstate is not None:
            self._writeback(self._fstate["p"], self._fstate["b"])

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)


def _amp_ctx(level):
    import contextlib

    if level in ("O1", "O2"):
        from ..amp import auto_cast

        return auto_cast(True, level=level, dtype="bfloat16")
    return contextlib.nullcontext()


def _safe_len(loader):
    try:
        return len(loader)
    except Exception:
        return None


def summary(net, input_size=None, dtypes=None):
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<24}{'Param #':<12}", "-" * (width + 36)]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(list(shape)):<24}{n:<12}")
    lines.append("-" * (width + 36))
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
