"""FLOPs of a static Program (reference: python/paddle/hapi/static_flops.py
— VarWrapper/OpWrapper/GraphWrapper over a Program + count_element_op /
count_convNd / count_linear). The tape Operator already carries typed
inputs/outputs with static shapes, so counting walks block.ops directly.

Counting convention matches dynamic_flops (and the reference): MACs for
conv/linear/matmul (no x2), element counts for activations/norms, zero for
shape-only ops.
"""
from __future__ import annotations

import numpy as np

from ..static.program import Variable

__all__ = ["static_flops"]


def _numel(shape):
    return int(np.prod([s for s in shape if s and s > 0])) if shape else 0


def _out_shape(op, i=0):
    try:
        return tuple(op.outputs[i]._value.shape)
    except Exception:
        return ()


def _in_shape(op, i=0):
    t = op.inputs[i]
    try:
        return tuple(t._value.shape)
    except Exception:
        return ()


_ELEMENT_OPS = {
    "relu", "relu6", "sigmoid", "tanh", "gelu", "exp", "sqrt", "log", "silu",
    "leaky_relu", "elu", "selu", "mish", "swish", "softplus", "add",
    "subtract", "multiply", "divide", "maximum", "minimum", "scale", "pow",
    "dropout", "softmax", "log_softmax", "abs", "square",
}
_ZERO_OPS = {
    "reshape", "transpose", "flatten", "concat", "split", "cast", "share",
    "folded_constant", "embedding", "one_hot", "pad", "slice", "gather",
    "stack", "unsqueeze", "squeeze", "full", "t", "assign",
}


def _count_op(op):
    t = op.type.split("/")[-1]
    out = _out_shape(op)
    if t in ("conv2d", "conv1d", "conv3d", "depthwise_conv2d"):
        # y.numel() * (in_c/groups * prod(kernel)) MACs (reference
        # static_flops count_convNd)
        w = _in_shape(op, 1)  # [out_c, in_c/groups, *k]
        if not w or not out:
            return 0
        return _numel(out) * _numel(w[1:])
    if t in ("linear", "matmul", "mul", "fc"):
        # out.numel() * reduced_dim MACs (count_linear / count_mul)
        x = _in_shape(op, 0)
        w = _in_shape(op, 1)
        if not out or not w:
            return 0
        if t == "linear" or t == "fc":
            k = w[0]  # weight [in, out]
        else:
            # matmul: reduction dim = x's last (or second-to-last when
            # trans_x) — attrs carry the flags since the export work
            k = x[-2] if op.attrs.get("trans_x") else (x[-1] if x else 0)
        return _numel(out) * int(k or 0)
    if t in ("batch_norm", "layer_norm", "group_norm", "instance_norm"):
        return 2 * _numel(out)  # normalize + affine (reference count_bn)
    if t in ("pool", "pool2d", "avg_pool2d", "max_pool2d",
             "adaptive_avg_pool2d", "adaptive_max_pool2d"):
        return _numel(out)
    if t in _ELEMENT_OPS:
        return _numel(out)
    if t in _ZERO_OPS:
        return 0
    # default: one op per output element (reference counts unknown ops 0;
    # element-cost is the safer floor for fused jax lowerings)
    return _numel(out)


def static_flops(program, print_detail=False):
    """Total forward FLOPs (MAC convention) of `program`'s global block
    (reference: hapi/static_flops.py static_flops(program))."""
    rows = []
    total = 0
    for op in program.global_block.ops:
        n = _count_op(op)
        total += n
        if print_detail:
            rows.append((op.type, _out_shape(op), n))
    if print_detail:
        for t, shape, n in rows:
            print(f"{t:28s} {str(shape):24s} {n:>14,}")
        print(f"Total FLOPs: {total}")
    return total
