"""paddle.flops — per-layer FLOP accounting (reference:
python/paddle/hapi/dynamic_flops.py flops()/dynamic_flops(): forward hooks
count multiply-adds per layer type).

Same hook-driven design over this framework's Layer: run one forward on a
zeros input, record per-layer input/output shapes, apply the standard
counting rules. Returns total FLOPs; print_detail emits a per-layer table.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _count(layer, x_shape, y_shape):
    """Op count for one layer call, by type — the reference convention is
    MACs WITHOUT doubling for linear/conv (dynamic_flops.py count_linear:
    total_mul * num_elements; count_convNd: y.numel() * (in/groups * prod(k)),
    reference lines 123-150), and elementwise counts for norm/activation."""
    from .. import nn

    if isinstance(layer, nn.Linear):
        return _numel(x_shape[:-1]) * layer.weight.shape[0] * layer.weight.shape[1]
    if isinstance(layer, (nn.Conv2D, nn.Conv1D, nn.Conv3D)):
        w = layer.weight  # [out_c, in_c/groups, *k]
        macs_per_out = _numel(w.shape[1:])
        return _numel(y_shape) * macs_per_out
    if isinstance(layer, (nn.Conv2DTranspose, nn.Conv1DTranspose,
                          nn.Conv3DTranspose)):
        # transpose weights are [in, out/groups, *k]: each output element
        # sums over in_channels/groups * prod(k) taps
        w = layer.weight
        groups = getattr(layer, "_groups", 1)
        macs_per_out = (w.shape[0] // groups) * _numel(w.shape[2:])
        return _numel(y_shape) * macs_per_out
    if isinstance(layer, (nn.BatchNorm, nn.BatchNorm1D, nn.BatchNorm2D,
                          nn.BatchNorm3D, nn.LayerNorm, nn.GroupNorm,
                          nn.InstanceNorm1D, nn.InstanceNorm2D,
                          nn.InstanceNorm3D)):
        return 2 * _numel(y_shape)
    if isinstance(layer, (nn.ReLU, nn.ReLU6, nn.GELU, nn.Sigmoid, nn.Tanh,
                          nn.LeakyReLU, nn.Hardswish, nn.Hardsigmoid,
                          nn.Silu, nn.PReLU, nn.ELU, nn.Softmax)):
        return _numel(y_shape)
    if isinstance(layer, (nn.AvgPool1D, nn.AvgPool2D, nn.MaxPool1D,
                          nn.MaxPool2D, nn.AdaptiveAvgPool1D,
                          nn.AdaptiveAvgPool2D, nn.AdaptiveMaxPool2D)):
        return _numel(y_shape)
    if isinstance(layer, nn.Embedding):
        return 0
    return 0


def flops(net, input_size=None, custom_ops=None, print_detail=False):
    """Total forward FLOPs of `net` on `input_size` (list incl. batch dim).
    A static Program counts through hapi.static_flops (reference
    hapi/dynamic_flops.py flops() dispatches the same way)."""
    from ..static.program import Program

    if isinstance(net, Program):
        from .static_flops import static_flops

        return static_flops(net, print_detail=print_detail)
    from .. import nn

    rows = []
    total = [0]
    custom_ops = custom_ops or {}

    hooks = []

    def make_hook(layer):
        def hook(lyr, inputs, output):
            if lyr._sub_layers:  # only count leaves
                return
            x_shape = list(inputs[0].shape) if inputs else []
            y = output[0] if isinstance(output, (tuple, list)) else output
            y_shape = list(y.shape) if isinstance(y, Tensor) else []
            fn = custom_ops.get(type(lyr))
            n = int(fn(lyr, x_shape, y_shape)) if fn else _count(lyr, x_shape, y_shape)
            total[0] += n
            params = sum(int(np.prod(p.shape)) for p in lyr.parameters(include_sublayers=False))
            rows.append((type(lyr).__name__, x_shape, y_shape, params, n))

        return hook

    for lyr in net.sublayers(include_self=True):
        hooks.append(lyr.register_forward_post_hook(make_hook(lyr)))

    was_training = net.training
    net.eval()
    try:
        x = Tensor(np.zeros(list(input_size), np.float32))
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    if print_detail:
        print(f"{'Layer':<24}{'Input':<20}{'Output':<20}{'Params':>10}{'FLOPs':>14}")
        for name, xs, ys, p, n in rows:
            print(f"{name:<24}{str(xs):<20}{str(ys):<20}{p:>10}{n:>14}")
        print(f"Total FLOPs: {total[0]}")
    return total[0]
