"""Model hub: load entrypoints from a `hubconf.py` protocol directory
(reference: python/paddle/hapi/hub.py:170,214,256).

The reference supports three sources: 'github', 'gitee' (both fetch an
archive over the network) and 'local'. This build runs in a zero-egress
environment, so the local source is fully supported and the network sources
raise a clear RuntimeError at call time (the repo-spec parsing and cache
layout mirror the reference so code migrates unchanged once egress exists).
"""
from __future__ import annotations

import os
import sys

__all__ = []

MODULE_HUBCONF = "hubconf.py"
VAR_DEPENDENCY = "dependencies"
HUB_DIR = os.path.expanduser(os.path.join("~", ".cache", "paddle_tpu", "hub"))


def _import_module(name, repo_dir):
    """reference: hapi/hub.py:38 — import hubconf.py from repo_dir."""
    import importlib.util

    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    try:
        spec.loader.exec_module(module)
    finally:
        sys.modules.pop(name, None)
    return module


def _parse_repo_info(repo, source):
    """reference: hapi/hub.py:63 — 'owner/name[:branch]' → parts."""
    if ":" in repo:
        repo_info, branch = repo.split(":")
    else:
        repo_info, branch = repo, "main" if source == "github" else "master"
    owner, repo_name = repo_info.split("/")
    return owner, repo_name, branch


def _get_cache_or_reload(repo, force_reload, verbose=True, source="github"):
    """reference: hapi/hub.py:81 — network archive fetch; gated here."""
    owner, repo_name, branch = _parse_repo_info(repo, source)
    cached = os.path.join(
        HUB_DIR, "_".join([owner, repo_name, branch.replace("/", "_")])
    )
    if os.path.exists(cached) and not force_reload:
        return cached
    raise RuntimeError(
        f"source='{source}' requires network access, which this environment "
        f"does not have; pre-populate {cached} or use source='local' with a "
        "directory containing hubconf.py"
    )


def _check_module_exists(name):
    import importlib.util

    return importlib.util.find_spec(name) is not None


def _check_dependencies(m):
    """reference: hapi/hub.py:158 — verify hubconf's `dependencies` list."""
    dependencies = getattr(m, VAR_DEPENDENCY, None)
    if dependencies is not None:
        missing = [pkg for pkg in dependencies if not _check_module_exists(pkg)]
        if missing:
            raise RuntimeError(
                f"Missing dependencies: {missing}"
            )


def _load_entry_from_hubconf(m, name):
    """reference: hapi/hub.py:135."""
    if not isinstance(name, str):
        raise ValueError("Invalid input: model should be a str of function name")
    func = getattr(m, name, None)
    if func is None or not callable(func):
        raise RuntimeError(f"Cannot find callable {name} in hubconf")
    return func


def _repo_dir(repo_dir, source, force_reload):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: "github" | "gitee" | "local".'
        )
    if source in ("github", "gitee"):
        return _get_cache_or_reload(repo_dir, force_reload, True, source)
    return repo_dir


def list(repo_dir, source="github", force_reload=False):
    """List callable entrypoints exported by the repo's hubconf.py
    (reference: hapi/hub.py:170)."""
    repo_dir = _repo_dir(repo_dir, source, force_reload)
    hub_module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    return [
        f
        for f in dir(hub_module)
        if callable(getattr(hub_module, f)) and not f.startswith("_")
    ]


def help(repo_dir, model, source="github", force_reload=False):
    """Docstring of one hub entrypoint (reference: hapi/hub.py:214)."""
    repo_dir = _repo_dir(repo_dir, source, force_reload)
    hub_module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    return _load_entry_from_hubconf(hub_module, model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Instantiate a hub entrypoint (reference: hapi/hub.py:256)."""
    repo_dir = _repo_dir(repo_dir, source, force_reload)
    hub_module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    _check_dependencies(hub_module)
    return _load_entry_from_hubconf(hub_module, model)(**kwargs)
