"""Automatic mixed precision.

Reference analog: python/paddle/amp/ (auto_cast.py, grad_scaler.py) + C++ cast
hooks in imperative/amp_auto_cast.cc. TPU-native: bf16 is the default low dtype
(MXU-native, no loss scaling needed); fp16+GradScaler supported for parity.
auto_cast installs a dtype-policy on the op dispatch layer: matmul/conv run in
low precision (O1 white-list semantics), reductions/norms stay fp32.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .grad_scaler import GradScaler  # noqa: F401

_tls = threading.local()

# O1 lists mirror the reference's amp lists (imperative/amp_auto_cast.cc white/black)
WHITE_OPS = {"matmul", "linear", "conv2d", "conv1d", "conv3d", "bmm", "mm", "einsum",
             "scaled_dot_product_attention"}
BLACK_OPS = {"reduce_sum", "softmax_with_cross_entropy", "cross_entropy", "layer_norm",
             "batch_norm", "norm", "mse_loss", "log_softmax"}


def amp_state():
    return getattr(_tls, "amp", None)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = amp_state()
    if enable:
        white = set(WHITE_OPS)
        black = set(BLACK_OPS)
        if custom_white_list:
            white |= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
        _tls.amp = {"level": level, "dtype": dtype, "white": white, "black": black}
    else:
        _tls.amp = None
    try:
        yield
    finally:
        _tls.amp = prev


amp_guard = auto_cast


def maybe_cast_inputs(op_name, arrays):
    """Called from the dispatch layer: cast op inputs per the active policy."""
    st = amp_state()
    if st is None:
        return arrays
    from ..core.dtype import to_jax_dtype

    low = to_jax_dtype(st["dtype"])
    if st["level"] == "O2":
        if op_name in st["black"]:
            return [a.astype(jnp.float32) if _is_low(a) else a for a in arrays]
        return [a.astype(low) if _is_float(a) else a for a in arrays]
    if op_name in st["white"]:
        return [a.astype(low) if _is_float(a) else a for a in arrays]
    if op_name in st["black"]:
        return [a.astype(jnp.float32) if _is_low(a) else a for a in arrays]
    return arrays


def _is_float(a):
    return hasattr(a, "dtype") and a.dtype in (jnp.float32, jnp.float16, jnp.bfloat16)


def _is_low(a):
    return hasattr(a, "dtype") and a.dtype in (jnp.float16, jnp.bfloat16)


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to low precision; optimizer keeps fp32 master weights
    (reference: paddle.amp.decorate)."""
    if level == "O2":
        single = not isinstance(models, (list, tuple))
        for m in [models] if single else models:
            m.to(dtype=dtype)
            m._casted_dtype = dtype
        if optimizers is not None:
            opts = [optimizers] if not isinstance(optimizers, (list, tuple)) else optimizers
            for o in opts:
                o._multi_precision = True
    if optimizers is None:
        return models
    return models, optimizers
