"""Build configuration accessors (reference: python/paddle/sysconfig.py:20,38).

Points at the directories custom-op builds (`utils.custom_op` /
cpp_extension-style workflows) need: the C-ABI sources that define the
native runtime interface, and the lazily-built shared library.
"""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory with the framework's native-interface sources
    (sysconfig.py:20). The csrc C-ABI files double as the headers: every
    exported symbol is `extern "C"` with a documented signature."""
    import paddle_tpu

    return os.path.abspath(
        os.path.join(os.path.dirname(paddle_tpu.__file__), os.pardir, "csrc")
    )


def get_lib():
    """Directory containing libpaddle_tpu_runtime.so (sysconfig.py:38).

    The runtime builds lazily into ~/.cache/paddle_tpu (runtime/native.py);
    calling this triggers the build so the returned dir actually holds the
    library, matching the reference's contract that get_lib() is linkable.
    """
    from .runtime import native

    if native.lib is None:
        native.build()
    return str(native._CACHE)
