"""fluid.dygraph — 1.x imperative-mode aliases (reference fluid/dygraph/).

Dygraph is this framework's default mode, so `guard()` only ensures static
mode is off for its scope.
"""
from __future__ import annotations

import contextlib

import paddle_tpu as paddle
from ..nn import Layer  # noqa: F401
from ..nn.layer import Layer as Layer_  # noqa: F401
from ..distributed.parallel import DataParallel  # noqa: F401
from ..jit import to_static as _to_static  # noqa: F401


def to_variable(value, name=None, zero_copy=None, dtype=None):
    return paddle.to_tensor(value, dtype=dtype)


@contextlib.contextmanager
def guard(place=None):
    was_static = paddle.in_static_mode() if hasattr(
        paddle, "in_static_mode") else False
    if was_static:
        paddle.disable_static()
    try:
        yield
    finally:
        if was_static:
            paddle.enable_static()


def enabled():
    return True


no_grad = paddle.no_grad


class Linear(Layer):
    """1.x dygraph.Linear(input_dim, output_dim, act=...) — pre-2.0
    signature over nn.Linear (reference fluid/dygraph/nn.py Linear)."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        from ..nn import Linear as _Linear2

        self._fc = _Linear2(input_dim, output_dim, weight_attr=param_attr,
                            bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = self._fc(x)
        if self._act:
            from ..nn import functional as F

            out = getattr(F, self._act)(out)
        return out


class Embedding(Layer):
    """1.x dygraph.Embedding(size=[vocab, dim]) (reference
    fluid/dygraph/nn.py Embedding)."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__()
        from ..nn import Embedding as _Emb2

        self._emb = _Emb2(size[0], size[1], padding_idx=padding_idx,
                          sparse=is_sparse, weight_attr=param_attr)

    def forward(self, x):
        return self._emb(x)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    import paddle_tpu as _p

    return _p.grad(outputs, inputs, grad_outputs=grad_outputs,
                   retain_graph=retain_graph, create_graph=create_graph,
                   allow_unused=allow_unused)


def save_dygraph(state_dict, model_path):
    """reference: fluid/dygraph/checkpoint.py save_dygraph — suffix chosen
    by content (.pdparams for params, .pdopt for optimizer state)."""
    import paddle_tpu as _p

    is_opt = any(not hasattr(v, "numpy") for v in state_dict.values()) and \
        any(k in ("LR_Scheduler", "global_step") or "_moment" in k or
            "beta" in k for k in state_dict)
    _p.save(state_dict, model_path + (".pdopt" if is_opt else ".pdparams"))


def load_dygraph(model_path):
    """reference: load_dygraph — returns (param_dict, opt_dict)."""
    import os

    import paddle_tpu as _p

    params = _p.load(model_path + ".pdparams") if os.path.exists(
        model_path + ".pdparams") else None
    opt = _p.load(model_path + ".pdopt") if os.path.exists(
        model_path + ".pdopt") else None
    return params, opt


def enable_dygraph(place=None):
    import paddle_tpu as _p

    _p.disable_static()


def disable_dygraph():
    import paddle_tpu as _p

    _p.enable_static()


disabled_dygraph = disable_dygraph  # 1.x spelling seen in the wild
