"""fluid.dygraph — 1.x imperative-mode aliases (reference fluid/dygraph/).

Dygraph is this framework's default mode, so `guard()` only ensures static
mode is off for its scope.
"""
from __future__ import annotations

import contextlib

import paddle_tpu as paddle
from ..nn import Layer  # noqa: F401
from ..nn.layer import Layer as Layer_  # noqa: F401
from ..distributed.parallel import DataParallel  # noqa: F401
from ..jit import to_static as _to_static  # noqa: F401


def to_variable(value, name=None, zero_copy=None, dtype=None):
    return paddle.to_tensor(value, dtype=dtype)


@contextlib.contextmanager
def guard(place=None):
    was_static = paddle.in_static_mode() if hasattr(
        paddle, "in_static_mode") else False
    if was_static:
        paddle.disable_static()
    try:
        yield
    finally:
        if was_static:
            paddle.enable_static()


def enabled():
    return True


no_grad = paddle.no_grad
