"""fluid.initializer — 1.x initializer aliases (reference
fluid/initializer.py spellings over nn.initializer classes)."""
from __future__ import annotations

from ..nn import initializer as _init

Constant = ConstantInitializer = _init.Constant
Normal = NormalInitializer = _init.Normal
TruncatedNormal = TruncatedNormalInitializer = _init.TruncatedNormal
Uniform = UniformInitializer = _init.Uniform
Xavier = XavierInitializer = _init.XavierNormal
XavierUniform = _init.XavierUniform
MSRA = MSRAInitializer = _init.KaimingNormal
Bilinear = getattr(_init, "Bilinear", None)
NumpyArrayInitializer = _init.Assign
