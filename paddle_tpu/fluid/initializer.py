"""fluid.initializer — 1.x initializer aliases (reference
fluid/initializer.py spellings over nn.initializer classes)."""
from __future__ import annotations

from ..nn import initializer as _init

Constant = ConstantInitializer = _init.Constant
Normal = NormalInitializer = _init.Normal
TruncatedNormal = TruncatedNormalInitializer = _init.TruncatedNormal
Uniform = UniformInitializer = _init.Uniform
Xavier = XavierInitializer = _init.XavierNormal
XavierUniform = _init.XavierUniform
MSRA = MSRAInitializer = _init.KaimingNormal
Bilinear = getattr(_init, "Bilinear", None)
NumpyArrayInitializer = _init.Assign


BilinearInitializer = Bilinear

_global_initializer = [None]


def set_global_initializer(weight_init, bias_init=None):
    """reference: fluid/initializer.py set_global_initializer — default
    initializers for subsequently created parameters. Layers consult
    nn.initializer defaults; this records the override for them."""
    from ..nn import initializer as _ni

    _global_initializer[0] = (weight_init, bias_init)
    if hasattr(_ni, "_set_global_initializer"):
        _ni._set_global_initializer(weight_init, bias_init)
