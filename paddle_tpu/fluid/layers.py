"""fluid.layers — 1.x layer-function aliases (reference fluid/layers/*).

Ops keep their fluid argument spellings (dim/keep_dim, pool_type, act=...)
and delegate to the 2.x lowerings.
"""
from __future__ import annotations

import paddle_tpu as paddle
from .. import nn as _nn
from ..nn import functional as F
from ..static import data as _static_data
from ..static.nn import (  # noqa: F401
    batch_norm,
    conv2d,
    conv2d_transpose,
    conv3d,
    crf_decoding,
    embedding,
    fc as _fc,
    group_norm,
    instance_norm,
    layer_norm,
    nce,
    prelu,
    row_conv,
    sequence_concat,
    sequence_conv,
    sequence_enumerate,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_reverse,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
    sparse_embedding,
)

# direct re-exports where 1.x and 2.x agree
concat = paddle.concat
reshape = paddle.reshape
transpose = paddle.transpose
cast = paddle.cast
assign = paddle.assign
shape = paddle.shape
zeros = paddle.zeros
ones = paddle.ones
relu = F.relu
sigmoid = F.sigmoid
tanh = paddle.tanh
softmax = F.softmax
softmax_with_cross_entropy = F.softmax_with_cross_entropy
square = paddle.square
sqrt = paddle.sqrt
abs = paddle.abs  # noqa: A001 — fluid spelling
log = paddle.log
exp = paddle.exp
clip = paddle.clip
stack = paddle.stack
gather = paddle.gather
scatter = paddle.scatter
one_hot = F.one_hot
label_smooth = F.label_smooth
sequence_mask = F.sequence_mask


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    """fluid semantics: `input` is PROBABILITIES (softmax already applied)
    and the result is the PER-EXAMPLE loss [N, 1] — not 2.x's
    logits+mean-reduce (fluid/layers/loss.py cross_entropy)."""
    out = F.cross_entropy(input, label, soft_label=soft_label,
                          ignore_index=ignore_index, use_softmax=False,
                          reduction="none")
    return paddle.unsqueeze(out, -1) if len(out.shape) == 1 else out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    """fluid semantics: default downgrade_in_infer — kept values UNSCALED
    at train time, activations scaled by (1-p) at inference."""
    if is_test:
        if dropout_implementation == "downgrade_in_infer":
            return x * (1.0 - dropout_prob)
        return x
    return F.dropout(x, p=dropout_prob, training=True,
                     mode="upscale_in_train"
                     if dropout_implementation == "upscale_in_train"
                     else "downgrade_in_infer")


def expand(x, expand_times, name=None):
    """fluid expand == TILE by repeat counts (2.x renamed it paddle.tile;
    paddle.expand broadcasts to a target shape — different op)."""
    return paddle.tile(x, expand_times)


def split(input, num_or_sections, dim=-1, name=None):
    """fluid default splits the LAST dim and spells the axis `dim`."""
    return paddle.split(input, num_or_sections, axis=dim)


def data(name, shape, dtype="float32", append_batch_size=True, lod_level=0,
         type=None, stop_gradient=True):
    """fluid.layers.data: 1.x semantics prepend an implicit -1 batch dim
    (fluid.data / 2.x static.data do NOT — that alias lives at the fluid
    package root)."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return _static_data(name, shape, dtype)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """fluid spelling (act=/param_attr=) over static.nn.fc."""
    return _fc(input, size, num_flatten_dims=num_flatten_dims,
               weight_attr=param_attr, bias_attr=bias_attr, activation=act,
               name=name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """reference mul_op: flatten x to 2-D at x_num_col_dims and y at
    y_num_col_dims, matmul, restore x.shape[:xd] + y.shape[yd:]."""
    import numpy as np

    xs, ys = list(x.shape), list(y.shape)
    xm = paddle.reshape(x, [int(np.prod(xs[:x_num_col_dims]) or 1),
                            int(np.prod(xs[x_num_col_dims:]) or 1)])
    ym = paddle.reshape(y, [int(np.prod(ys[:y_num_col_dims]) or 1),
                            int(np.prod(ys[y_num_col_dims:]) or 1)])
    out = paddle.matmul(xm, ym)
    return paddle.reshape(out, xs[:x_num_col_dims] + ys[y_num_col_dims:])


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    out = paddle.matmul(x, y, transpose_x=transpose_x,
                        transpose_y=transpose_y)
    return out * alpha if alpha != 1.0 else out


def mean(x, name=None):
    return paddle.mean(x)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return paddle.mean(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return paddle.sum(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return paddle.max(input, axis=dim, keepdim=keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return paddle.min(input, axis=dim, keepdim=keep_dim)


def _align_y(x, y, axis):
    """fluid mid-axis broadcasting: y's dims align with x STARTING AT
    `axis` (elementwise_op semantics) — append trailing 1-dims so numpy
    broadcasting reproduces it."""
    if axis == -1 or not hasattr(y, "shape"):
        return y
    trailing = len(x.shape) - axis - len(y.shape)
    if trailing <= 0:
        return y
    return paddle.reshape(y, list(y.shape) + [1] * trailing)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.add(x, _align_y(x, y, axis)), act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.subtract(x, _align_y(x, y, axis)), act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.multiply(x, _align_y(x, y, axis)), act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.divide(x, _align_y(x, y, axis)), act)


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    return paddle.full(shape, value, dtype=dtype)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           name=None, data_format="NCHW"):
    if global_pooling:
        if pool_type == "max":
            return F.adaptive_max_pool2d(input, 1)
        return F.adaptive_avg_pool2d(input, 1)
    if pool_type == "max":
        return F.max_pool2d(input, pool_size, stride=pool_stride,
                            padding=pool_padding, ceil_mode=ceil_mode)
    return F.avg_pool2d(input, pool_size, stride=pool_stride,
                        padding=pool_padding, ceil_mode=ceil_mode)


def flatten(x, axis=1, name=None):
    """fluid flatten: ALWAYS 2-D — [prod(shape[:axis]), prod(shape[axis:])]
    (2.x flatten(start_axis, stop_axis) is a different op)."""
    import numpy as np

    xs = list(x.shape)
    # np.prod([]) == 1.0, and zero-size dims must stay 0 — no `or 1` fixups
    return paddle.reshape(x, [int(np.prod(xs[:axis])),
                              int(np.prod(xs[axis:]))])


def topk(input, k, name=None):
    return paddle.topk(input, k)  # last dim, values+indices (same in 1.x)


def argmax(x, axis=0, name=None):
    return paddle.argmax(x, axis=axis)  # 1.x default axis=0 (2.x flattens)


def argmin(x, axis=0, name=None):
    return paddle.argmin(x, axis=axis)


def squeeze(input, axes, name=None):
    # fluid: empty axes means squeeze EVERY size-1 dim
    return paddle.squeeze(input, axis=axes if axes else None)


def unsqueeze(input, axes, name=None):
    return paddle.unsqueeze(input, axis=axes)


def pad(x, paddings, pad_value=0.0, name=None):
    """fluid pad: flat [before0, after0, before1, after1, ...] list."""
    return paddle.nn.functional.pad(
        x, paddings, value=pad_value, mode="constant")


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    return paddle.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    if seed:  # seeded draws must be reproducible (paddle.normal has no seed)
        import jax
        import jax.numpy as jnp

        arr = mean + std * jax.random.normal(
            jax.random.key(seed), tuple(int(s) for s in shape))
        return paddle.to_tensor(arr.astype(jnp.dtype(dtype)))
    return paddle.normal(mean=mean, std=std, shape=shape).astype(dtype)


def _eager_only(op_name):
    """Host-computed legacy ops read concrete values (.numpy()); under
    static-graph build a Variable holds only a placeholder, so running them
    there would SILENTLY return results computed from zeros. Fail loudly
    instead (the silent-failure class VERDICT r2/r3 flagged)."""
    from ..framework import in_dynamic_mode

    if not in_dynamic_mode():
        raise NotImplementedError(
            f"fluid.layers.{op_name} computes on host values and has no "
            "static-graph lowering; call it in dygraph mode (or move it "
            "outside the program_guard)")


def _maybe_act(out, act):
    if act is None:
        return out
    return getattr(F, act)(out)


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


# --------------------------------------------------------------- batch 3
# (reference fluid/layers/{nn,tensor,ops,loss,control_flow,detection,
# learning_rate_scheduler,sequence_lod,rnn}.py — the long tail of 1.x
# names, each keeping its fluid spelling and delegating to 2.x lowerings)

# ---- activations / simple math
def leaky_relu(x, alpha=0.02, name=None):
    return F.leaky_relu(x, negative_slope=alpha)


def elu(x, alpha=1.0, name=None):
    return F.elu(x, alpha=alpha)


def relu6(x, threshold=6.0, name=None):
    # fluid's threshold arg is honored (2.x relu6 hardcodes 6)
    return paddle.clip(x, 0.0, threshold)


def selu(x, scale=None, alpha=None, name=None):
    kw = {}
    if scale is not None:
        kw["scale"] = scale
    if alpha is not None:
        kw["alpha"] = alpha
    return F.selu(x, **kw)


def mish(x, threshold=20, name=None):
    # softplus with the fluid threshold cutoff: x > threshold passes through
    sp = paddle.where(
        paddle.greater_than(x, paddle.full([], float(threshold), "float32")),
        x, F.softplus(x))
    return paddle.multiply(x, paddle.tanh(sp))


def swish(x, beta=1.0, name=None):
    return paddle.multiply(x, F.sigmoid(paddle.scale(x, scale=beta)))


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    # honor fluid's threshold/scale/offset (2.x hardswish fixes 6/6/3)
    return paddle.multiply(
        x, paddle.scale(paddle.clip(paddle.scale(x, bias=offset),
                                    0.0, threshold), scale=1.0 / scale))


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return F.hardsigmoid(x, slope=slope, offset=offset)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return paddle.clip(x, t_min, t_max)


def soft_relu(x, threshold=40.0, name=None):
    return paddle.log(paddle.scale(paddle.exp(paddle.clip(
        x, -threshold, threshold)), bias=1.0))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return paddle.stanh(x, scale_a=scale_a, scale_b=scale_b)


def maxout(x, groups, name=None, axis=1):
    return F.maxout(x, groups, axis=axis)


def pow(x, factor=1.0, name=None):  # noqa: A001
    return paddle.pow(x, factor)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.maximum(x, y), act)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.minimum(x, y), act)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.mod(x, y), act)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.floor_divide(x, y), act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.pow(x, y), act)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return F.normalize(x, p=2, axis=axis, epsilon=epsilon)


def cos_sim(X, Y):
    out = F.cosine_similarity(X, Y, axis=1)
    return paddle.reshape(out, [-1, 1])


def clip_by_norm(x, max_norm, name=None):
    norm = paddle.sqrt(paddle.sum(paddle.multiply(x, x)))
    factor = paddle.minimum(
        paddle.full([], 1.0, "float32"),
        paddle.divide(paddle.full([], float(max_norm), "float32"),
                      paddle.maximum(norm, paddle.full([], 1e-12, "float32"))))
    return paddle.multiply(x, factor)


def sign(x, name=None):
    return paddle.sign(x)


# ---- reductions / logic / comparison
def reduce_all(input, dim=None, keep_dim=False, name=None):
    return paddle.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return paddle.any(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return paddle.prod(input, axis=dim, keepdim=keep_dim)


def equal(x, y, cond=None, name=None):
    return paddle.equal(x, y)


def not_equal(x, y, cond=None, name=None):
    return paddle.not_equal(x, y)


def greater_than(x, y, cond=None, name=None):
    return paddle.greater_than(x, y)


def greater_equal(x, y, cond=None, name=None):
    return paddle.greater_equal(x, y)


def less_than(x, y, force_cpu=None, cond=None, name=None):
    return paddle.less_than(x, y)


def less_equal(x, y, cond=None, name=None):
    return paddle.less_equal(x, y)


def logical_and(x, y, out=None, name=None):
    return paddle.logical_and(x, y)


def logical_or(x, y, out=None, name=None):
    return paddle.logical_or(x, y)


def logical_xor(x, y, out=None, name=None):
    return paddle.logical_xor(x, y)


def logical_not(x, out=None, name=None):
    return paddle.logical_not(x)


def is_empty(x, name=None):
    return paddle.to_tensor(bool(int(paddle.numel(x).numpy()) == 0)) \
        if not paddle.in_dynamic_mode() is False else \
        paddle.equal(paddle.numel(x), paddle.full([], 0, "int64"))


def isfinite(x, name=None):
    return paddle.all(paddle.isfinite(x))


def has_inf(x):
    return paddle.any(paddle.isinf(x))


def has_nan(x):
    return paddle.any(paddle.isnan(x))


# ---- tensor creation / manipulation
def create_tensor(dtype, name=None, persistable=False):
    return paddle.to_tensor(__import__("numpy").zeros((), dtype))


def argsort(input, axis=-1, descending=False, name=None):
    ids = paddle.argsort(input, axis=axis, descending=descending)
    vals = paddle.sort(input, axis=axis, descending=descending)
    return vals, ids


def linspace(start, stop, num, dtype="float32", name=None):
    return paddle.linspace(start, stop, num, dtype=dtype)


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32",
        name=None):
    out = paddle.eye(num_rows, num_columns, dtype=dtype)
    if batch_shape:
        for _ in batch_shape:
            out = paddle.unsqueeze(out, 0)
        out = paddle.expand(out, list(batch_shape) + list(out.shape[-2:]))
    return out


def ones_like(x, out=None, name=None):
    return paddle.ones_like(x)


def zeros_like(x, out=None, name=None):
    return paddle.zeros_like(x)


def diag(diagonal, name=None):
    return paddle.diag(diagonal)


def triu(input, diagonal=0, name=None):
    return paddle.triu(input, diagonal)


def range(start, end, step, dtype, name=None):  # noqa: A001
    return paddle.arange(start, end, step, dtype)


def reverse(x, axis, name=None):
    return paddle.flip(x, axis if isinstance(axis, (list, tuple)) else [axis])


def multiplex(inputs, index, name=None):
    return paddle.multiplex(inputs, index)


def strided_slice(input, axes, starts, ends, strides, name=None):
    return paddle.strided_slice(input, axes, starts, ends, strides)


def slice(input, axes, starts, ends, name=None):  # noqa: A001
    return paddle.slice(input, axes, starts, ends)


def crop_tensor(x, shape=None, offsets=None, name=None):
    return paddle.crop(x, shape=shape, offsets=offsets)


def crop(x, shape=None, offsets=None, name=None):
    return paddle.crop(x, shape=shape, offsets=offsets)


def expand_as(x, target_tensor, name=None):
    return paddle.expand_as(x, target_tensor)


def gather_nd(input, index, name=None):
    return paddle.gather_nd(input, index)


def scatter_nd(index, updates, shape, name=None):
    return paddle.scatter_nd(index, updates, shape)


def scatter_nd_add(ref, index, updates, name=None):
    return paddle.scatter_nd_add(ref, index, updates)


def unstack(x, axis=0, num=None):
    return paddle.unstack(x, axis=axis, num=num)


def unbind(input, axis=0):
    return paddle.unbind(input, axis=axis)


def unique(x, dtype="int32"):
    out, index = paddle.unique(x, return_index=True)
    return out, paddle.cast(index, dtype)


def unique_with_counts(x, dtype="int32"):
    out, index, counts = paddle.unique(x, return_index=True,
                                       return_counts=True)
    return out, paddle.cast(index, dtype), paddle.cast(counts, dtype)


def increment(x, value=1.0, in_place=True):
    out = paddle.scale(x, bias=float(value))
    if in_place and hasattr(x, "_value"):
        x._value = out._value
        return x
    return out


def rank(input):
    return paddle.rank(input)


def size(input):
    return paddle.numel(input)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return paddle.shard_index(input, index_num, nshards, shard_id,
                              ignore_value)


def sums(input, out=None):
    total = input[0]
    for t in input[1:]:
        total = paddle.add(total, t)
    return total


def sum(x):  # noqa: A001
    if isinstance(x, (list, tuple)):
        return sums(x)
    return paddle.sum(x)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return F.pad(input, list(paddings), mode=mode.replace("edge", "replicate"),
                 value=pad_value, data_format=data_format)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    pads = []
    for xs, ys in zip(x.shape, y.shape):
        pads += [0, int(xs) - int(ys)]
    return F.pad(y, pads, value=pad_value)


def space_to_depth(x, blocksize, name=None):
    return F.pixel_unshuffle(x, blocksize)


def shuffle_channel(x, group, name=None):
    return F.channel_shuffle(x, group)


def pixel_shuffle(x, upscale_factor):
    return F.pixel_shuffle(x, upscale_factor)


def fsp_matrix(x, y):
    b, cx = x.shape[0], x.shape[1]
    cy = y.shape[1]
    h, w = x.shape[2], x.shape[3]
    xf = paddle.reshape(x, [b, cx, -1])
    yf = paddle.reshape(y, [b, cy, -1])
    return paddle.scale(paddle.matmul(xf, paddle.transpose(yf, [0, 2, 1])),
                        scale=1.0 / float(int(h) * int(w)))


def add_position_encoding(input, alpha, beta, name=None):
    import numpy as _np

    b, s, d = (int(v) for v in input.shape)
    pos = _np.arange(s, dtype="float32")[:, None]
    half = d // 2
    div = _np.power(10000.0, -_np.arange(half, dtype="float32") / half)
    enc = _np.zeros((s, d), "float32")
    enc[:, :half] = _np.sin(pos * div)
    enc[:, half:2 * half] = _np.cos(pos * div)
    return paddle.add(paddle.scale(input, scale=alpha),
                      paddle.scale(paddle.to_tensor(enc), scale=beta))


# ---- losses
def mse_loss(input, label):
    return F.mse_loss(input, label)


def square_error_cost(input, label):
    return F.square_error_cost(input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    return F.log_loss(input, label, epsilon)


def kldiv_loss(x, target, reduction="mean", name=None):
    return F.kl_div(x, target, reduction=reduction)


def huber_loss(input, label, delta):
    diff = paddle.subtract(input, label)
    abs_diff = paddle.abs(diff)
    quad = paddle.scale(paddle.multiply(diff, diff), scale=0.5)
    lin = paddle.scale(paddle.subtract(abs_diff,
                                       paddle.full([], delta / 2.0,
                                                   "float32")), scale=delta)
    return paddle.where(paddle.less_equal(
        abs_diff, paddle.full([], float(delta), "float32")), quad, lin)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    diff = paddle.subtract(x, y)
    if inside_weight is not None:
        diff = paddle.multiply(diff, inside_weight)
    sigma2 = (sigma if sigma is not None else 1.0) ** 2
    abs_diff = paddle.abs(diff)
    thresh = paddle.full([], 1.0 / sigma2, "float32")
    quad = paddle.scale(paddle.multiply(diff, diff), scale=0.5 * sigma2)
    lin = paddle.subtract(abs_diff, paddle.full([], 0.5 / sigma2, "float32"))
    out = paddle.where(paddle.less_than(abs_diff, thresh), quad, lin)
    if outside_weight is not None:
        out = paddle.multiply(out, outside_weight)
    return paddle.sum(out, axis=-1, keepdim=True)


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    loss = F.binary_cross_entropy_with_logits(x, label, reduction="none")
    mask = paddle.cast(paddle.not_equal(
        label, paddle.full([], float(ignore_index), label.dtype)), x.dtype)
    loss = paddle.multiply(loss, mask)
    if normalize:
        loss = paddle.divide(loss, paddle.maximum(
            paddle.sum(mask), paddle.full([], 1.0, x.dtype)))
    return loss


def bpr_loss(input, label, name=None):
    """Bayesian pairwise ranking (reference: fluid/layers/loss.py bpr_loss):
    mean over the C-1 NEGATIVE classes of -log(sigmoid(pos - neg))."""
    n_class = int(input.shape[-1])
    onehot = F.one_hot(paddle.reshape(label, [-1]), n_class)
    pos = paddle.sum(paddle.multiply(input, onehot), axis=-1, keepdim=True)
    diff = paddle.subtract(input, pos)
    loss = paddle.scale(paddle.log(paddle.scale(
        F.sigmoid(paddle.scale(diff, scale=-1.0)), bias=1e-8)), scale=-1.0)
    # exclude the positive column from the average (divisor C-1)
    neg_mask = paddle.scale(onehot, scale=-1.0, bias=1.0)
    total = paddle.sum(paddle.multiply(loss, neg_mask), axis=-1, keepdim=True)
    return paddle.scale(total, scale=1.0 / max(n_class - 1, 1))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return F.npair_loss(anchor, positive, labels, l2_reg)


def rank_loss(label, left, right, name=None):
    out = paddle.subtract(left, right)
    return paddle.add(
        paddle.subtract(F.softplus(out), paddle.multiply(label, out)),
        paddle.zeros_like(out))


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return F.margin_ranking_loss(left, right, label, margin=margin,
                                 reduction="none")


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference: fluid/layers/loss.py teacher_student_sigmoid_loss —
    z = clip(x); loss = log(1+exp(-|z|)) + max(z,0) - z*label."""
    z = paddle.clip(input, soft_max_lower_bound, soft_max_up_bound)
    return paddle.subtract(
        paddle.add(F.softplus(paddle.scale(paddle.abs(z), scale=-1.0)),
                   paddle.maximum(z, paddle.zeros_like(z))),
        paddle.multiply(z, label))


def dice_loss(input, label, epsilon=1e-5):
    return F.dice_loss(input, label, epsilon)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    return F.sigmoid_focal_loss(x, label, normalizer=fg_num, alpha=alpha,
                                gamma=gamma, reduction="none")


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """reference: fluid/layers/loss.py center_loss — distance to a running
    class-center table (the table updates eagerly like BN stats)."""
    import numpy as _np

    key = "_center_loss_centers_%d_%d" % (num_classes, int(input.shape[-1]))
    store = center_loss.__dict__.setdefault("tables", {})
    if key not in store:
        store[key] = paddle.to_tensor(
            _np.zeros((num_classes, int(input.shape[-1])), "float32"))
    centers = store[key]
    picked = F.embedding(paddle.reshape(label, [-1]), centers)
    diff = paddle.subtract(input, picked)
    loss = paddle.scale(paddle.sum(paddle.multiply(diff, diff),
                                   axis=-1, keepdim=True), scale=0.5)
    if update_center and paddle.in_dynamic_mode():
        import jax.numpy as _jnp

        lv = _np.asarray(paddle.reshape(label, [-1]).numpy())
        dv = _np.asarray(diff.numpy())
        counts = _np.bincount(lv, minlength=num_classes)[:, None] + 1.0
        upd = _np.zeros(centers.shape, "float32")
        _np.add.at(upd, lv, dv)
        centers._value = centers._value + _jnp.asarray(
            alpha * upd / counts)
    return loss


# ---- resize family
def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "LINEAR": "linear",
            "BICUBIC": "bicubic"}[resample.upper()]
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode=mode, align_corners=align_corners,
                         data_format=data_format)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode, data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True, data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners, 1, data_format)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format="NCW"):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode="linear", align_corners=align_corners,
                         data_format=data_format)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode="trilinear", align_corners=align_corners,
                         data_format=data_format)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    ratio = out_short_len / float(short)
    return image_resize(input, [int(round(h * ratio)), int(round(w * ratio))],
                        resample=resample)


# ---- vision extras
def grid_sampler(x, grid, name=None):
    return F.grid_sample(x, grid)


def affine_grid(theta, out_shape, name=None):
    return F.affine_grid(theta, out_shape)


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    shape = [1, -1, 1, 1] if data_layout == "NCHW" else [1, 1, 1, -1]
    out = x
    if scale is not None:
        out = paddle.multiply(out, paddle.reshape(scale, shape))
    if bias is not None:
        out = paddle.add(out, paddle.reshape(bias, shape))
    return _maybe_act(out, act)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return F.temporal_shift(x, seg_num, shift_ratio)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return F.unfold(x, kernel_sizes, strides, paddings, dilations)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    cols = F.unfold(input, filter_size, stride, padding)
    return paddle.transpose(cols, [0, 2, 1])


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    return F.local_response_norm(input, size=n, alpha=alpha * n, beta=beta,
                                 k=k, data_format=data_format)


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if pool_type == "max":
        return F.adaptive_max_pool2d(input, pool_size,
                                     return_mask=require_index)
    return F.adaptive_avg_pool2d(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if pool_type == "max":
        return F.adaptive_max_pool3d(input, pool_size,
                                     return_mask=require_index)
    return F.adaptive_avg_pool3d(input, pool_size)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCDHW"):
    if global_pooling:
        pool_size = [int(s) for s in input.shape[2:]]
        pool_padding = 0
    if pool_type == "max":
        return F.max_pool3d(input, pool_size, pool_stride, pool_padding,
                            ceil_mode=ceil_mode, data_format=data_format)
    return F.avg_pool3d(input, pool_size, pool_stride, pool_padding,
                        ceil_mode=ceil_mode, exclusive=exclusive,
                        data_format=data_format)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    layer = _nn.Conv3DTranspose(
        int(input.shape[1]), num_filters, filter_size, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr, data_format=data_format)
    return _maybe_act(layer(input), act)


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    layer = _nn.Bilinear(int(x.shape[-1]), int(y.shape[-1]), size)
    return _maybe_act(layer(x, y), act)


# ---- detection (vision/ops lowerings)
def iou_similarity(x, y, box_normalized=True, name=None):
    from ..vision.ops import iou_similarity as _impl

    return _impl(x, y, box_normalized)


def box_clip(input, im_info, name=None):
    from ..vision.ops import box_clip as _impl

    return _impl(input, im_info)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    from ..vision.ops import prior_box as _impl

    return _impl(input, image, min_sizes, max_sizes, aspect_ratios, variance,
                 flip, clip, steps, offset, min_max_aspect_ratios_order)


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    from ..vision.ops import anchor_generator as _impl

    return _impl(input, anchor_sizes, aspect_ratios, variance, stride, offset)


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    from ..vision.ops import bipartite_match as _impl

    return _impl(dist_matrix, match_type, dist_threshold)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    from ..vision.ops import multiclass_nms as _impl

    return _impl(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                 nms_threshold, normalized, nms_eta, background_label)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0):
    from ..vision.ops import yolo_box as _impl

    return _impl(x, img_size, anchors, class_num, conf_thresh,
                 downsample_ratio, clip_bbox, scale_x_y=scale_x_y)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    from ..vision.ops import box_coder as _impl

    return _impl(prior_box, prior_box_var, target_box, code_type,
                 box_normalized, axis=axis)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_num=None):
    from ..vision.ops import roi_align as _impl

    return _impl(input, rois, rois_num, (pooled_height, pooled_width),
                 spatial_scale, sampling_ratio)


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_num=None, name=None):
    from ..vision.ops import roi_pool as _impl

    return _impl(input, rois, rois_num, (pooled_height, pooled_width),
                 spatial_scale)


# ---- learning-rate decay (fluid functions → 2.x LRScheduler objects; the
# reference migration guide maps them the same way)
def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    return paddle.optimizer.lr.NoamDecay(d_model, warmup_steps, learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    if staircase:
        return paddle.optimizer.lr.StepDecay(learning_rate, decay_steps,
                                             decay_rate)
    return paddle.optimizer.lr.ExponentialDecay(
        learning_rate, decay_rate ** (1.0 / decay_steps))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    import math as _math

    if staircase:
        return paddle.optimizer.lr.StepDecay(
            learning_rate, decay_steps, _math.exp(-decay_rate))
    return paddle.optimizer.lr.ExponentialDecay(
        learning_rate, _math.exp(-decay_rate / decay_steps))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    return paddle.optimizer.lr.InverseTimeDecay(
        learning_rate, decay_rate / decay_steps)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    return paddle.optimizer.lr.PolynomialDecay(
        learning_rate, decay_steps, end_learning_rate, power, cycle)


def piecewise_decay(boundaries, values):
    return paddle.optimizer.lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    return paddle.optimizer.lr.CosineAnnealingDecay(
        learning_rate, step_each_epoch * epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    return paddle.optimizer.lr.LinearWarmup(learning_rate, warmup_steps,
                                            start_lr, end_lr)


# ---- control flow / arrays / misc
def while_loop(cond, body, loop_vars, is_test=False, name=None):
    from ..static import while_loop as _impl

    return _impl(cond, body, loop_vars)


def cond(pred, true_fn=None, false_fn=None, name=None):
    from ..static import cond as _impl

    return _impl(pred, true_fn, false_fn)


def case(pred_fn_pairs, default=None, name=None):
    from ..static import case as _impl

    return _impl(pred_fn_pairs, default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    from ..static import switch_case as _impl

    return _impl(branch_index, branch_fns, default)


def create_array(dtype):
    return []


def array_write(x, i, array=None):
    if array is None:
        array = []
    idx = int(i.numpy()) if hasattr(i, "numpy") else int(i)
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    idx = int(i.numpy()) if hasattr(i, "numpy") else int(i)
    return array[idx]


def array_length(array):
    return paddle.to_tensor(__import__("numpy").int64(len(array)))


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    items = [t for t in input if t is not None]
    out = paddle.stack(items, axis=axis) if use_stack \
        else paddle.concat(items, axis=axis)
    sizes = paddle.to_tensor(__import__("numpy").asarray(
        [int(t.shape[axis]) if not use_stack else 1 for t in items], "int32"))
    return out, sizes


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    store = autoincreased_step_counter.__dict__.setdefault("counters", {})
    key = counter_name or "@STEP_COUNTER@"
    val = store.get(key, begin - step) + step
    store[key] = val
    return paddle.to_tensor(__import__("numpy").int64(val))


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):  # noqa: A002
    _eager_only("sampling_id")
    import numpy as _np

    probs = _np.asarray(x.numpy(), "float64")
    rng = _np.random.RandomState(seed if seed else None)
    ids = [rng.choice(probs.shape[1], p=row / row.sum()) for row in probs]
    return paddle.to_tensor(_np.asarray(ids, "int64"))


def Assert(cond, data=None, summarize=20, name=None):
    import numpy as _np

    ok = bool(_np.all(_np.asarray(cond.numpy()))) if hasattr(cond, "numpy") \
        else bool(cond)
    if not ok:
        raise ValueError(
            f"Assert failed: {[_np.asarray(d.numpy())[:summarize] for d in (data or [])]}")
    return cond


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    from ..static.extras import py_func as _impl

    return _impl(func, x, out, backward_func)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance per pair (reference:
    fluid/layers/nn.py edit_distance → edit_distance_op). Host computation —
    the op is inherently data-dependent-loop shaped."""
    _eager_only("edit_distance")
    import numpy as _np
    from builtins import range as _range  # module-level `range` shadows it

    a = _np.asarray(input.numpy())
    b = _np.asarray(label.numpy())
    n = a.shape[0]
    dists = _np.zeros((n, 1), "float32")
    seq_num = paddle.to_tensor(_np.int64(n))
    for k in _range(n):
        s = a[k][: int(input_length.numpy()[k])] if input_length is not None \
            else a[k]
        t = b[k][: int(label_length.numpy()[k])] if label_length is not None \
            else b[k]
        if ignored_tokens:
            s = [v for v in s if v not in ignored_tokens]
            t = [v for v in t if v not in ignored_tokens]
        m, l = len(s), len(t)
        dp = _np.arange(l + 1, dtype="float32")
        for i in _range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in _range(1, l + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (s[i - 1] != t[j - 1]))
        d = dp[l]
        dists[k, 0] = d / max(l, 1) if normalized else d
    return paddle.to_tensor(dists), seq_num


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    return F.ctc_loss(input, label, input_length, label_length, blank=blank,
                      reduction="none")


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    _eager_only("ctc_greedy_decoder")
    import numpy as _np

    probs = _np.asarray(input.numpy())
    ids = probs.argmax(-1)  # [B, T] or [T, B]? fluid uses [T*B, C] LoD; take batch-major
    if ids.ndim == 1:
        ids = ids[None]
    outs = []
    lens = []
    for row in ids:
        dedup = [int(v) for i, v in enumerate(row)
                 if v != blank and (i == 0 or v != row[i - 1])]
        outs.append(dedup)
        lens.append(len(dedup))
    width = max(1, max(lens))
    canvas = _np.full((len(outs), width), padding_value, "int64")
    for i, o in enumerate(outs):
        canvas[i, : len(o)] = o
    return paddle.to_tensor(canvas), paddle.to_tensor(
        _np.asarray(lens, "int64"))


# ---- rnn api (2.x cells/layers back the 1.x names)
RNNCell = _nn.SimpleRNNCell
GRUCell = _nn.GRUCell
LSTMCell = _nn.LSTMCell


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    layer = _nn.RNN(cell, is_reverse=is_reverse, time_major=time_major)
    return layer(inputs, initial_states, sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    layer = _nn.BiRNN(cell_fw, cell_bw, time_major=time_major)
    return layer(inputs, initial_states, sequence_length)


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    hidden = size // 4
    layer = _nn.LSTM(int(input.shape[-1]), hidden,
                     direction="backward" if is_reverse else "forward")
    init = None
    if h_0 is not None:
        init = (paddle.unsqueeze(h_0, 0), paddle.unsqueeze(c_0, 0))
    out, (h, c) = layer(input, init)
    return out, c


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False):
    layer = _nn.GRU(int(input.shape[-1]), size,
                    direction="backward" if is_reverse else "forward")
    init = paddle.unsqueeze(h_0, 0) if h_0 is not None else None
    out, h = layer(input, init)
    return out


def dynamic_lstmp(input, size, proj_size, **kwargs):
    out, c = dynamic_lstm(input, size, **{k: v for k, v in kwargs.items()
                                          if k in ("h_0", "c_0", "is_reverse")})
    proj = _nn.Linear(size // 4, proj_size)
    return proj(out), c


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    layer = _nn.LSTM(int(input.shape[-1]), hidden_size, num_layers=num_layers,
                     direction="bidirect" if is_bidirec else "forward",
                     dropout=dropout_prob, time_major=True)
    out, (h, c) = layer(input, (init_h, init_c))
    return out, h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    cell = _nn.GRUCell(int(input.shape[-1]), size // 3)
    h = cell(input, hidden)
    return h[0], h[1], h[0]


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    cell = _nn.LSTMCell(int(x_t.shape[-1]), int(hidden_t_prev.shape[-1]))
    h, (hh, cc) = cell(x_t, (hidden_t_prev, cell_t_prev))
    return hh, cc


# --------------------------------------------------------------- batch 4
# decode family, distributions, legacy control-flow classes, detection tail,
# selected-rows/LoD utilities (reference fluid/layers/{rnn,distributions,
# control_flow,detection,nn,tensor}.py)

# ---- decode family (nn.decode backs the 1.x names)
from ..nn.decode import (  # noqa: F401,E402
    BeamSearchDecoder,
    Decoder,
    dynamic_decode,
    gather_tree,
)


class DecodeHelper:
    """Sampling-strategy protocol for BasicDecoder (reference:
    fluid/layers/rnn.py DecodeHelper): initialize/sample/next_inputs."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing: read the next ground-truth step (rnn.py
    TrainingHelper). Trace-safe: dynamic_decode drives steps inside
    lax.while_loop, so time indexing uses dynamic_index_in_dim."""

    def __init__(self, inputs, sequence_length, time_major=False):
        self.inputs = inputs
        self.sequence_length = sequence_length
        self.time_major = time_major
        self._axis = 0 if time_major else 1
        self._steps = int(inputs.shape[self._axis])

    def _step_input(self, time):
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor as _T

        x = self.inputs._value if hasattr(self.inputs, "_value") \
            else jnp.asarray(self.inputs)
        t = jnp.clip(jnp.asarray(time), 0, self._steps - 1)
        return _T(jax.lax.dynamic_index_in_dim(x, t, self._axis,
                                               keepdims=False))

    def initialize(self):
        import jax.numpy as jnp

        from ..core.tensor import Tensor as _T

        sl = self.sequence_length._value if hasattr(
            self.sequence_length, "_value") else jnp.asarray(
            self.sequence_length)
        return self._step_input(0), _T(jnp.zeros(sl.shape, bool))

    def sample(self, time, outputs, states):
        return paddle.argmax(outputs, axis=-1)

    def next_inputs(self, time, outputs, states, sample_ids):
        import jax.numpy as jnp

        from ..core.tensor import Tensor as _T

        sl = self.sequence_length._value if hasattr(
            self.sequence_length, "_value") else jnp.asarray(
            self.sequence_length)
        next_t = jnp.asarray(time) + 1
        finished = _T(next_t >= sl.astype(next_t.dtype))
        return finished, self._step_input(next_t), states


class GreedyEmbeddingHelper(DecodeHelper):
    """Feed back argmax through an embedding fn (rnn.py
    GreedyEmbeddingHelper)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = start_tokens
        self.end_token = int(end_token)

    def initialize(self):
        import jax.numpy as jnp

        from ..core.tensor import Tensor as _T

        st = self.start_tokens._value if hasattr(self.start_tokens, "_value") \
            else jnp.asarray(self.start_tokens)
        return self.embedding_fn(self.start_tokens), _T(
            jnp.zeros(st.shape, bool))

    def sample(self, time, outputs, states):
        return paddle.argmax(outputs, axis=-1)

    def next_inputs(self, time, outputs, states, sample_ids):
        import jax.numpy as jnp

        from ..core.tensor import Tensor as _T

        ids = sample_ids._value if hasattr(sample_ids, "_value") \
            else jnp.asarray(sample_ids)
        finished = _T(ids.astype(jnp.int64) == self.end_token)
        return finished, self.embedding_fn(sample_ids), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Multinomial sampling feedback (rnn.py SampleEmbeddingHelper) —
    jax.random.categorical with a time-folded key, trace-safe."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature
        self.seed = seed if seed is not None else 0

    def sample(self, time, outputs, states):
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor as _T

        logits = outputs._value if hasattr(outputs, "_value") \
            else jnp.asarray(outputs)
        if self.temperature is not None:
            logits = logits / self.temperature
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                 jnp.asarray(time))
        return _T(jax.random.categorical(key, logits, axis=-1))


class BasicDecoder(Decoder):
    """cell + helper + output layer (reference: rnn.py BasicDecoder).
    step returns ((cell_outputs, sample_ids), next_states, next_inputs,
    finished) like the reference's BasicDecoder.OutputWrapper."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        (initial_inputs, initial_finished) = self.helper.initialize()
        return initial_inputs, initial_cell_states, initial_finished

    def step(self, time, inputs, states, **kwargs):
        cell_outputs, cell_states = self.cell(inputs, states, **kwargs)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        sample_ids = self.helper.sample(time, cell_outputs, cell_states)
        finished, next_inputs, next_states = self.helper.next_inputs(
            time, cell_outputs, cell_states, sample_ids)
        return (cell_outputs, sample_ids), next_states, next_inputs, finished


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One beam-search step (reference: fluid/layers/rnn.py beam_search →
    beam_search_op): flat candidate top-k over beam_size*V accumulated
    scores. Finished beams (pre_ids last token == end_id) are HELD: all
    their candidates are masked to -inf except re-emitting end_id at the
    frozen pre_score, like the reference op. Static-shape form over
    [batch*beam, V] scores."""
    import numpy as _np

    sc = scores if is_accumulated else paddle.add(
        paddle.log(scores), paddle.reshape(pre_scores, [-1, 1]))
    b_times_k = int(sc.shape[0])
    v = int(sc.shape[1])
    batch = b_times_k // beam_size
    if pre_ids is not None:
        fin = paddle.equal(
            paddle.reshape(paddle.cast(pre_ids, "int64"), [-1, 1]),
            paddle.full([b_times_k, 1], float(end_id), "int64"))
        end_col = paddle.cast(F.one_hot(
            paddle.full([b_times_k], float(end_id), "int64"), v), "bool")
        hold = paddle.where(
            end_col,
            paddle.expand_as(paddle.reshape(pre_scores, [-1, 1]), sc),
            paddle.full(sc.shape, -1e9, "float32"))
        sc = paddle.where(paddle.expand_as(fin, sc), hold, sc)
    flat = paddle.reshape(sc, [batch, beam_size * v])
    top_scores, top_idx = paddle.topk(flat, beam_size)
    parent = paddle.floor_divide(
        top_idx, paddle.full(top_idx.shape, v, top_idx.dtype))
    token = paddle.mod(top_idx, paddle.full(top_idx.shape, v, top_idx.dtype))
    selected_ids = paddle.reshape(token, [-1, 1])
    selected_scores = paddle.reshape(top_scores, [-1, 1])
    offsets = paddle.to_tensor(
        (_np.arange(batch, dtype="int64") * beam_size)[:, None])
    parent_flat = paddle.reshape(
        paddle.add(paddle.cast(parent, "int64"), offsets), [-1])
    if return_parent_idx:
        return selected_ids, selected_scores, parent_flat
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    """Backtrace beam parents into full sequences (reference:
    beam_search_decode_op): ids/scores are per-step lists of
    (token [batch*beam, 1], parent_flat [batch*beam]) as produced by
    beam_search(return_parent_idx=True). gather_tree runs on the
    [T, batch, beam] view with WITHIN-BATCH parent indices
    (parent_flat mod beam_size)."""
    toks = paddle.cast(paddle.stack(
        [paddle.reshape(t, [-1]) for t, _ in ids], axis=0), "int64")
    parents = paddle.cast(paddle.stack(
        [paddle.reshape(p, [-1]) for _, p in ids], axis=0), "int64")
    t_steps = int(toks.shape[0])
    batch = int(toks.shape[1]) // beam_size
    toks3 = paddle.reshape(toks, [t_steps, batch, beam_size])
    par3 = paddle.mod(
        paddle.reshape(parents, [t_steps, batch, beam_size]),
        paddle.full([t_steps, batch, beam_size], beam_size, "int64"))
    from ..nn.decode import gather_tree as _gather

    seqs = _gather(toks3, par3)
    sc = paddle.stack([paddle.reshape(v, [-1]) for v in scores], axis=0)
    return paddle.reshape(seqs, [t_steps, -1]), sc


# ---- distributions (fluid.layers.distributions → paddle.distribution)
from ..distribution import Categorical, Normal, Uniform  # noqa: F401,E402


class MultivariateNormalDiag:
    """reference: fluid/layers/distributions.py MultivariateNormalDiag —
    diagonal-covariance Gaussian over the last axis."""

    def __init__(self, loc, scale):
        self.loc = loc
        self.scale = scale  # diagonal COVARIANCE matrix per the reference

    def _diag(self):
        import numpy as _np

        return paddle.to_tensor(_np.diagonal(
            _np.asarray(self.scale.numpy()), axis1=-2, axis2=-1).copy())

    def entropy(self):
        import numpy as _np

        d = self._diag()
        k = int(d.shape[-1])
        return paddle.scale(paddle.sum(paddle.log(d), axis=-1), scale=0.5,
                            bias=0.5 * k * float(_np.log(2 * _np.pi * _np.e)))

    def kl_divergence(self, other):
        d0, d1 = self._diag(), other._diag()
        delta = paddle.subtract(self.loc, other.loc)
        term = paddle.sum(paddle.divide(
            paddle.add(d0, paddle.multiply(delta, delta)), d1), axis=-1)
        k = float(d0.shape[-1])
        logdet = paddle.subtract(paddle.sum(paddle.log(d1), axis=-1),
                                 paddle.sum(paddle.log(d0), axis=-1))
        return paddle.scale(paddle.add(paddle.subtract(
            term, paddle.full(term.shape, k, "float32")), logdet), scale=0.5)


# ---- direct aliases / trivial
scale = paddle.scale
where = paddle.where


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    from ..static import auc as _impl

    return _impl(input, label, curve=curve, num_thresholds=num_thresholds)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..static import create_parameter as _impl

    return _impl(shape, dtype, name=name, attr=attr, is_bias=is_bias,
                 default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..static import create_global_var as _impl

    return _impl(shape, value, dtype, persistable=persistable, name=name)


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    from ..static import Print as _impl

    return _impl(input, first_n=first_n, message=message, summarize=summarize)


def load(out, file_path, load_as_fp16=None):
    from ..framework.io import load_binary_tensor

    arr, _lod = load_binary_tensor(file_path)
    out._value = paddle.to_tensor(arr)._value
    return out


def identity_loss(x, reduction="none"):
    """reference: identity_loss op (the IPU loss-marker primitive)."""
    if reduction in (0, "sum"):
        return paddle.sum(x)
    if reduction in (1, "mean"):
        return paddle.mean(x)
    return x


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    w = create_parameter([num_classes - 1, int(input.shape[-1])], "float32")
    b = create_parameter([num_classes - 1], "float32", is_bias=True)
    return F.hsigmoid_loss(input, label, num_classes, w, b,
                           path_table=path_table, path_code=path_code)


def mean_iou(input, label, num_classes):
    """reference: mean_iou_op — per-class IoU from a confusion count."""
    _eager_only("mean_iou")
    import numpy as _np

    p = _np.asarray(input.numpy()).reshape(-1)
    g = _np.asarray(label.numpy()).reshape(-1)
    ious = []
    out_wrong = _np.zeros(num_classes, "int32")
    out_correct = _np.zeros(num_classes, "int32")
    for c in __import__("builtins").range(num_classes):
        inter = int(((p == c) & (g == c)).sum())
        union = int(((p == c) | (g == c)).sum())
        out_correct[c] = inter
        out_wrong[c] = union - inter
        if union:
            ious.append(inter / union)
    miou = float(_np.mean(ious)) if ious else 0.0
    return (paddle.to_tensor(_np.float32(miou)),
            paddle.to_tensor(out_wrong), paddle.to_tensor(out_correct))


def hash(input, hash_size, num_hash=1, name=None):  # noqa: A001
    """reference: hash_op (xxhash rows into buckets) — here a deterministic
    polynomial row-hash with num_hash independent salts."""
    _eager_only("hash")
    import numpy as _np

    x = _np.asarray(input.numpy(), "int64")
    outs = []
    for h in __import__("builtins").range(num_hash):
        salt = 1000003 + 7919 * h
        acc = _np.zeros(x.shape[0], "int64")
        for col in __import__("builtins").range(x.shape[1]):
            acc = acc * salt + x[:, col]
        outs.append(_np.abs(acc) % hash_size)
    return paddle.to_tensor(_np.stack(outs, -1).astype("int64"))


def random_crop(x, shape, seed=None):
    """reference: random_crop_op — crop `shape` from the TRAILING dims;
    leading dims (batch/channels) pass through."""
    _eager_only("random_crop")
    import numpy as _np

    xv = _np.asarray(x.numpy())
    rng = _np.random.RandomState(seed)
    off = xv.ndim - len(shape)
    starts = [rng.randint(0, xv.shape[off + i] - shape[i] + 1)
              for i in __import__("builtins").range(len(shape))]
    sl = tuple(_np.s_[s:s + l] for s, l in zip(starts, shape))
    return paddle.to_tensor(xv[(Ellipsis,) + sl])


def continuous_value_model(input, cvm, use_cvm=True):
    """reference: cvm_op — keep (use_cvm) or drop the leading show/click
    columns of CTR embeddings."""
    if use_cvm:
        return input
    return paddle.slice(input, [1], [2], [int(input.shape[1])])


def get_tensor_from_selected_rows(x, name=None):
    import numpy as _np

    dense = _np.zeros((x.height, *x.value.shape[1:]), x.value.dtype)
    dense[_np.asarray(x.rows)] = _np.asarray(x.value)
    return paddle.to_tensor(dense)


def merge_selected_rows(x, name=None):
    from ..core.selected_rows import SelectedRows
    import numpy as _np

    rows = _np.asarray(x.rows)
    vals = _np.asarray(x.value)
    uniq = _np.unique(rows)
    merged = _np.zeros((len(uniq), *vals.shape[1:]), vals.dtype)
    _np.add.at(merged, _np.searchsorted(uniq, rows), vals)
    return SelectedRows(rows=uniq.tolist(), value=merged, height=x.height)


def lod_reset(x, y=None, target_lod=None):
    """reference: lod_reset_op — re-segment x with new level-0 sequence
    LENGTHS (from y's lod, y's int values, or target_lod)."""
    import numpy as _np

    from ..core.ragged import LoDTensor

    values = x.value() if isinstance(x, LoDTensor) else x
    if y is not None:
        if isinstance(y, LoDTensor):
            lens = y.recursive_sequence_lengths()[-1]
        else:
            lens = [int(v) for v in _np.asarray(y.numpy()).reshape(-1)]
        return LoDTensor(values, [lens])
    return LoDTensor(values, [list(map(int, target_lod))])


def lod_append(x, level):
    """reference: lod_append — add an inner LoD level (lengths)."""
    from ..core.ragged import LoDTensor

    values = x.value() if isinstance(x, LoDTensor) else x
    lens = x.recursive_sequence_lengths() if isinstance(x, LoDTensor) else []
    return LoDTensor(values, lens + [list(map(int, level))])


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    shape = list(shape)
    shape[output_dim_idx] = int(input.shape[input_dim_idx])
    return paddle.full(shape, value, dtype=dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):  # noqa: A002
    shape = list(shape)
    shape[output_dim_idx] = int(input.shape[input_dim_idx])
    return uniform_random(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = int(input.shape[input_dim_idx])
    return gaussian_random(shape, mean=mean, std=std, seed=seed, dtype=dtype)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None, moving_mean_name=None,
              moving_variance_name=None, do_model_average_for_mean_and_var=True,
              slot_dim=-1, sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """reference: data_norm_op — normalization by accumulated batch
    statistics (size/sum/square-sum counters). Parameter sharing follows
    fluid's name-scoped reuse: a `name` keys one persistent accumulator;
    anonymous calls normalize by the CURRENT batch only (no cross-call
    state, since distinct call sites must not share counters)."""
    import numpy as _np

    d = int(input.shape[-1])
    if name is None:
        mean = paddle.mean(input, axis=0, keepdim=True)
        centered = paddle.subtract(input, mean)
        var = paddle.mean(paddle.multiply(centered, centered), axis=0,
                          keepdim=True)
        out = paddle.divide(centered, paddle.sqrt(paddle.add(
            var, paddle.full(var.shape, epsilon, "float32"))))
        return _maybe_act(out, act)
    key = (str(name), d)
    store = data_norm.__dict__.setdefault("stats", {})
    if key not in store:
        store[key] = {
            "size": _np.full(d, 1e4, "float32"),
            "sum": _np.zeros(d, "float32"),
            "sqsum": _np.full(d, 1e4, "float32"),
        }
    st = store[key]
    mean = paddle.to_tensor(st["sum"] / st["size"])
    scale_v = paddle.to_tensor(_np.sqrt(st["size"] / st["sqsum"]))
    out = paddle.multiply(paddle.subtract(input, mean), scale_v)
    if paddle.in_dynamic_mode():
        xv = _np.asarray(input.numpy()).reshape(-1, d)
        st["size"] += xv.shape[0]
        st["sum"] += xv.sum(0)
        st["sqsum"] += (xv ** 2).sum(0)
    return _maybe_act(out, act)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """reference: sample_logits_op — softmax CE over the true class plus
    num_samples uniformly sampled negatives."""
    import numpy as _np

    n, v = int(logits.shape[0]), int(logits.shape[1])
    rng = _np.random.RandomState(seed if seed else None)
    neg = rng.randint(0, v, (n, num_samples))
    lbl = _np.asarray(label.numpy()).reshape(n, num_true)
    if remove_accidental_hits:
        hit = neg == lbl[:, :1]
        neg = _np.where(hit, (neg + 1) % v, neg)
    idx = _np.concatenate([lbl[:, :1], neg], axis=1)  # [n, 1+S]
    gathered = paddle.index_sample(
        logits, paddle.to_tensor(idx.astype("int64")))
    sampled_label = paddle.to_tensor(_np.zeros(n, "int64"))
    return F.cross_entropy(gathered, sampled_label, reduction="none")


def linear_chain_crf(input, label, param_attr=None, length=None):
    """reference: linear_chain_crf_op — CRF negative log-likelihood via the
    forward algorithm. Returns (alpha, transition_exps?, emission_exps?,
    log_likelihood) in the reference; here (log_likelihood, transition)."""
    import jax
    import jax.numpy as jnp

    n_tags = int(input.shape[-1])
    trans = create_parameter([n_tags + 2, n_tags], "float32")

    from ..core.dispatch import primitive_call as _pc

    def f(emis, lbl, tr):
        start, stop, body = tr[0], tr[1], tr[2:]
        if emis.ndim == 2:
            emis = emis[None]
            lbl = lbl[None]
        b, t, k = emis.shape

        def fwd_one(e):
            def step(alpha, e_t):
                nxt = jax.scipy.special.logsumexp(
                    alpha[:, None] + body, axis=0) + e_t
                return nxt, None

            alpha0 = start + e[0]
            alphaT, _ = jax.lax.scan(step, alpha0, e[1:])
            return jax.scipy.special.logsumexp(alphaT + stop)

        logZ = jax.vmap(fwd_one)(emis)

        def score_one(e, y):
            em = jnp.take_along_axis(e, y[:, None], 1)[:, 0].sum()
            tr_sc = body[y[:-1], y[1:]].sum()
            return em + tr_sc + start[y[0]] + stop[y[-1]]

        gold = jax.vmap(score_one)(emis, lbl)
        return logZ - gold  # negative log-likelihood per sequence

    nll = _pc(f, input, paddle.cast(label, "int64").detach(), trans,
              name="linear_chain_crf")
    return nll, trans


# ---- detection tail
def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """reference: density_prior_box_op — dense grid of fixed-size boxes per
    cell (each density d contributes d*d shifted centers)."""
    import numpy as _np

    feat, img = input, image
    fh, fw = int(feat.shape[2]), int(feat.shape[3])
    ih, iw = int(img.shape[2]), int(img.shape[3])
    step_h = steps[1] if steps[1] > 0 else ih / fh
    step_w = steps[0] if steps[0] > 0 else iw / fw
    boxes = []
    for y in __import__("builtins").range(fh):
        for x in __import__("builtins").range(fw):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            for size, density in zip(fixed_sizes, densities):
                shift = size / density
                for ratio in fixed_ratios:
                    w = size * float(_np.sqrt(ratio))
                    h = size / float(_np.sqrt(ratio))
                    for r in __import__("builtins").range(density):
                        for c in __import__("builtins").range(density):
                            ccx = cx - size / 2 + shift / 2 + c * shift
                            ccy = cy - size / 2 + shift / 2 + r * shift
                            boxes.append([(ccx - w / 2) / iw,
                                          (ccy - h / 2) / ih,
                                          (ccx + w / 2) / iw,
                                          (ccy + h / 2) / ih])
    arr = _np.asarray(boxes, "float32").reshape(fh, fw, -1, 4)
    if clip:
        arr = arr.clip(0, 1)
    var = _np.broadcast_to(_np.asarray(variance, "float32"),
                           arr.shape).copy()
    if flatten_to_2d:
        arr = arr.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return paddle.to_tensor(arr), paddle.to_tensor(var)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """reference: matrix_nms_op (SOLOv2) — parallel soft-suppression via the
    pairwise IoU matrix instead of a sequential sweep."""
    _eager_only("matrix_nms")
    import numpy as _np

    from ..vision.ops import _box_iou as _iou

    out = []
    b = _np.asarray(bboxes.numpy())
    sc = _np.asarray(scores.numpy())
    c, n = sc.shape
    k = n if nms_top_k < 0 else min(nms_top_k, n)
    for ci in __import__("builtins").range(c):
        if ci == background_label:
            continue
        s = sc[ci]
        order = _np.argsort(-s)[:k]
        s_k = s[order]
        keepable = s_k > score_threshold
        boxes_k = b[order]
        import jax.numpy as jnp

        iou = _np.asarray(_iou(jnp.asarray(boxes_k), jnp.asarray(boxes_k)))
        iou = _np.triu(iou, 1)
        iou_cmax = iou.max(0)  # per-box max overlap with a higher-scored box
        if use_gaussian:
            decay = _np.exp(-(iou ** 2 - iou_cmax[None, :] ** 2)
                            / gaussian_sigma).min(0)
        else:
            decay = ((1 - iou) / _np.maximum(1 - iou_cmax[None, :],
                                             1e-10)).min(0)
        dec_s = s_k * decay
        for j in _np.nonzero(keepable & (dec_s > post_threshold))[0]:
            out.append([ci, dec_s[j], *boxes_k[j]])
    out.sort(key=lambda r: -r[1])
    if keep_top_k > 0:
        out = out[:keep_top_k]
    arr = _np.asarray(out, "float32") if out else _np.zeros((0, 6), "float32")
    res = paddle.to_tensor(arr)
    if return_rois_num:
        return res, paddle.to_tensor(_np.asarray([len(out)], "int32"))
    return res


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """SSD post-process (reference: detection.py detection_output):
    box_coder decode + multiclass_nms."""
    if (len(scores.shape) == 3 and int(scores.shape[0]) > 1) or \
            (len(loc.shape) == 3 and int(loc.shape[0]) > 1):
        raise NotImplementedError(
            "detection_output: batch > 1 needs per-image LoD output; run "
            "per image (static shapes carry no box->image map)")
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    if len(decoded.shape) == 3:
        decoded = paddle.squeeze(decoded, [0]) if int(decoded.shape[0]) == 1 \
            else decoded
    sc = scores
    if len(sc.shape) == 3:  # [1, P, C] -> [C, P]
        sc = paddle.transpose(paddle.squeeze(sc, [0]), [1, 0])
    return multiclass_nms(decoded, sc, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold,
                          background_label=background_label)


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """reference: target_assign_op — gather rows by match index, filling
    mismatches (index < 0) with mismatch_value."""
    _eager_only("target_assign")
    import numpy as _np

    x = _np.asarray(input.numpy())
    mi = _np.asarray(matched_indices.numpy())
    if x.ndim == 2:
        x = x[None]
    out = _np.full((mi.shape[0], mi.shape[1], x.shape[-1]),
                   mismatch_value if mismatch_value is not None else 0,
                   x.dtype)
    wt = _np.zeros((mi.shape[0], mi.shape[1], 1), "float32")
    for bidx in __import__("builtins").range(mi.shape[0]):
        pos = mi[bidx] >= 0
        out[bidx, pos] = x[min(bidx, x.shape[0] - 1)][mi[bidx, pos]]
        wt[bidx, pos] = 1.0
    return paddle.to_tensor(out), paddle.to_tensor(wt)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    _eager_only("box_decoder_and_assign")
    decoded = box_coder(prior_box, prior_box_var, target_box,
                        code_type="decode_center_size")
    import numpy as _np

    sc = _np.asarray(box_score.numpy())
    best = sc.argmax(-1)
    d = _np.asarray(decoded.numpy())
    if d.ndim == 2:  # single-class decode
        assigned = d
    else:
        assigned = d[_np.arange(d.shape[0]), best]
    return decoded, paddle.to_tensor(assigned)


def polygon_box_transform(input, name=None):
    """reference: polygon_box_transform_op — EAST-style geometry maps:
    offset channels become absolute quad coordinates."""
    _eager_only("polygon_box_transform")
    import numpy as _np

    x = _np.asarray(input.numpy())
    n, c, h, w = x.shape
    out = x.copy()
    xs = _np.arange(w)[None, None, None, :] * 4.0
    ys = _np.arange(h)[None, None, :, None] * 4.0
    out[:, 0::2] = xs - x[:, 0::2]
    out[:, 1::2] = ys - x[:, 1::2]
    return paddle.to_tensor(out.astype(x.dtype))


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=1, deformable_groups=1,
                    im2col_step=1, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    from ..vision.ops import deform_conv2d as _impl

    w = create_parameter(
        [num_filters, int(input.shape[1]) // groups,
         filter_size if isinstance(filter_size, int) else filter_size[0],
         filter_size if isinstance(filter_size, int) else filter_size[1]],
        "float32")
    return _impl(input, offset, w, stride=stride, padding=padding,
                 dilation=dilation, deformable_groups=deformable_groups,
                 groups=groups, mask=mask if modulated else None)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    from ..vision.ops import distribute_fpn_proposals as _impl

    return _impl(fpn_rois, min_level, max_level, refer_level, refer_scale,
                 rois_num=rois_num)


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None, name=None):
    """reference: collect_fpn_proposals_op — concat per-level RoIs and keep
    the global top-n by score."""
    rois = paddle.concat(multi_rois, axis=0)
    sc = paddle.reshape(paddle.concat(multi_scores, axis=0), [-1])
    k = min(post_nms_top_n, int(sc.shape[0]))
    _, idx = paddle.topk(sc, k)
    return paddle.gather(rois, idx)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    from ..vision.ops import generate_proposals as _impl

    return _impl(scores, bbox_deltas, im_info, anchors, variances,
                 pre_nms_top_n, post_nms_top_n, nms_thresh, min_size, eta,
                 return_rois_num=return_rois_num)


# ---- legacy control-flow classes
class While:
    """reference: control_flow.py While — block-style while. The body
    appends ops under `with while.block()`; here the modern while_loop is
    the engine and this wrapper keeps 1.x source compiling."""

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self._entered = False

    def block(self):
        raise NotImplementedError(
            "While.block() builds LoD-era blocks; port to "
            "fluid.layers.while_loop(cond_fn, body_fn, loop_vars) — same "
            "semantics, functional form (static/control_flow.py)")


class Switch:
    """reference: control_flow.py Switch — case/default context managers
    over switch_case."""

    def __init__(self, name=None):
        self._cases = []
        self._default = None

    def case(self, condition):
        raise NotImplementedError(
            "Switch.case blocks are LoD-era program surgery; port to "
            "fluid.layers.case(pred_fn_pairs, default) "
            "(static/control_flow.py)")

    def default(self):
        raise NotImplementedError(
            "Switch.default: port to fluid.layers.case(..., default=fn)")


class IfElse:
    """reference: control_flow.py IfElse — port to cond()."""

    def __init__(self, cond, name=None):
        raise NotImplementedError(
            "IfElse is LoD-era block surgery; port to "
            "fluid.layers.cond(pred, true_fn, false_fn)")


class StaticRNN:
    """reference: control_flow.py StaticRNN — fixed-length RNN unrolled at
    build time. step_input/memory/update_memory/step_output/() protocol."""

    def __init__(self, name=None):
        self._inputs = []
        self._memories = []
        self._outputs = []
        self._built = False

    def step(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield self

        return ctx()

    def step_input(self, x):
        self._inputs.append(x)
        self._seq_len = int(x.shape[0])
        return ("input", len(self._inputs) - 1)

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is None:
            import numpy as _np

            batch = int(batch_ref.shape[ref_batch_dim_idx]) if batch_ref is not None else 1
            init = paddle.full([batch] + list(shape)[1:], init_value,
                               "float32")
        self._memories.append({"init": init, "update": None})
        return ("mem", len(self._memories) - 1)

    def update_memory(self, mem, var):
        self._memories[mem[1]]["update"] = var

    def step_output(self, o):
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        raise NotImplementedError(
            "StaticRNN's deferred-block build is LoD-era; port to "
            "fluid.layers.rnn(cell, inputs) or paddle.nn.RNN — the cell "
            "closure replaces step_input/memory bookkeeping")


class DynamicRNN:
    """reference: control_flow.py DynamicRNN — LoD-driven variable-length
    RNN. Port to padded batches + paddle.nn.RNN with sequence_length."""

    def __init__(self, name=None):
        raise NotImplementedError(
            "DynamicRNN consumes LoD tensors; port to padded batches with "
            "fluid.layers.rnn(cell, inputs, sequence_length=...) — "
            "sequence_mask covers the ragged tail")


# ---- doc/codegen utilities (reference layers/layer_function_generator.py)
def generate_activation_fn(op_type):
    return getattr(F, op_type, None) or getattr(paddle, op_type)


def generate_inplace_fn(op_type):
    base = generate_activation_fn(op_type.rstrip("_"))

    def inplace_fn(x, name=None):
        from ..core.tape import graft_inplace

        return graft_inplace(x, base(x))

    return inplace_fn


def generate_layer_fn(op_type):
    return generate_activation_fn(op_type)


def templatedoc(op_type=None):
    def deco(fn):
        return fn

    return deco


def autodoc(comment=""):
    def deco(fn):
        return fn

    return deco


# ---- legacy reader plumbing: the modern path is io.DataLoader
def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    raise NotImplementedError(
        "py_reader/double_buffer are the deprecated 1.x feeding pipeline; "
        "use paddle.io.DataLoader (io/dataloader.py — multiprocess workers "
        "+ shared-memory channel) or paddle.batch readers")


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    return py_reader(capacity, None, None)


def double_buffer(reader, place=None, name=None):
    return reader  # prefetch is the DataLoader's job on this runtime


def read_file(reader, file_obj=None):
    raise NotImplementedError(
        "file readers are the deprecated 1.x pipeline; use "
        "paddle.io.DataLoader or paddle.reader decorators")


# ------------------------------------------------------------- batch 4b
def inplace_abn(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
                param_attr=None, bias_attr=None, data_layout="NCHW",
                name=None, moving_mean_name=None, moving_variance_name=None,
                do_model_average_for_mean_and_var=True, use_global_stats=False,
                act_alpha=1.0):
    """reference: inplace_abn_op — batch norm + activation fused in place;
    XLA fuses the chain anyway, so this is bn→act composition."""
    out = batch_norm(input, act=None, is_test=is_test, momentum=momentum,
                     epsilon=epsilon, param_attr=param_attr,
                     bias_attr=bias_attr, data_layout=data_layout,
                     use_global_stats=use_global_stats)
    if act == "leaky_relu":
        return leaky_relu(out, alpha=act_alpha)
    if act == "elu":
        return elu(out, alpha=act_alpha)
    return _maybe_act(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: spectral_norm_op — weight / sigma_max via power
    iteration on the [dim, -1] matricization."""
    import numpy as _np

    from ..core.dispatch import primitive_call as _pc

    d = int(dim)

    def f(w):
        import jax.numpy as jnp

        perm = [d] + [i for i in __import__("builtins").range(w.ndim)
                      if i != d]
        mat = jnp.transpose(w, perm).reshape(w.shape[d], -1)
        u = jnp.ones((mat.shape[0],), w.dtype)
        v = jnp.ones((mat.shape[1],), w.dtype)
        for _ in __import__("builtins").range(power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        return w / sigma

    return _pc(f, weight, name="spectral_norm")


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """reference: chunk_eval_op — chunk-level precision/recall/F1 for
    IOB/IOE/IOBES tagging."""
    _eager_only("chunk_eval")
    import numpy as _np

    def extract(tags):
        # tag id layout (reference): tag = chunk_type * n + pos
        n = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[chunk_scheme]
        chunks = []
        start = None
        ctype = None
        for i, t in enumerate(list(tags) + [-1]):
            pos = t % n if t >= 0 else -1
            ct = t // n if t >= 0 else -1
            begin = (t >= 0 and (
                (chunk_scheme == "IOB" and pos == 0)
                or (chunk_scheme == "IOBES" and pos in (0, 3))
                or chunk_scheme == "plain"
                or (chunk_scheme == "IOE" and (start is None or ct != ctype))))
            if start is not None and (t < 0 or begin or ct != ctype):
                chunks.append((start, i - 1, ctype))
                start = None
            if t >= 0 and begin:
                start, ctype = i, ct
        return {c for c in chunks
                if not excluded_chunk_types or c[2] not in excluded_chunk_types}

    inf = _np.asarray(input.numpy()).reshape(-1)
    lab = _np.asarray(label.numpy()).reshape(-1)
    if seq_length is not None:
        lens = _np.asarray(seq_length.numpy()).reshape(-1)
        off, inf_chunks, lab_chunks = 0, set(), set()
        for i, ln in enumerate(lens):
            inf_chunks |= {(i, *c) for c in extract(inf[off:off + ln])}
            lab_chunks |= {(i, *c) for c in extract(lab[off:off + ln])}
            off += ln
    else:
        inf_chunks = extract(inf)
        lab_chunks = extract(lab)
    correct = len(inf_chunks & lab_chunks)
    p = correct / len(inf_chunks) if inf_chunks else 0.0
    r = correct / len(lab_chunks) if lab_chunks else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    t = lambda v, dt="float32": paddle.to_tensor(_np.asarray(v, dt))
    return (t(p), t(r), t(f1), t(len(inf_chunks), "int64"),
            t(len(lab_chunks), "int64"), t(correct, "int64"))


def sequence_scatter(input, index, updates, name=None):
    """reference: sequence_scatter_op — per-sequence scatter-add of update
    rows into `input` at the LoD-segmented indices."""
    _eager_only("sequence_scatter")
    import numpy as _np

    from ..core.ragged import LoDTensor

    if not isinstance(index, LoDTensor):
        raise TypeError("sequence_scatter needs a LoDTensor index "
                        "(core/ragged.py) — the LoD maps updates to rows")
    x = _np.asarray(input.numpy()).copy()
    idx = _np.asarray(index.numpy()).reshape(-1)
    upd = _np.asarray(updates.numpy()).reshape(-1)
    offs = index.lod()[0]
    for row in __import__("builtins").range(len(offs) - 1):
        for k in __import__("builtins").range(offs[row], offs[row + 1]):
            x[row, idx[k]] += upd[k]
    return paddle.to_tensor(x)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    """reference: psroi_pool_op — position-sensitive RoI average pooling:
    input channel block (i,j) feeds only output bin (i,j)."""
    _eager_only("psroi_pool")
    import numpy as _np

    x = _np.asarray(input.numpy())
    r = _np.asarray(rois.numpy())
    n, c, h, w = x.shape
    ph, pw = pooled_height, pooled_width
    if c != output_channels * ph * pw:
        raise ValueError(
            f"psroi_pool: input channels ({c}) must equal "
            f"output_channels * pooled_height * pooled_width "
            f"({output_channels}*{ph}*{pw})")
    out = _np.zeros((r.shape[0], output_channels, ph, pw), "float32")
    # map each roi to its batch image: rois_num gives per-image counts
    if rois_num is not None:
        counts = _np.asarray(rois_num.numpy()
                             if hasattr(rois_num, "numpy") else rois_num,
                             "int64")
        img_of = _np.repeat(_np.arange(len(counts)), counts)
    else:
        img_of = _np.zeros(r.shape[0], "int64")
    for ri, roi in enumerate(r):
        bi = int(img_of[ri])
        x1, y1, x2, y2 = [v * spatial_scale for v in roi]
        rh = max(y2 - y1, 0.1) / ph
        rw = max(x2 - x1, 0.1) / pw
        for i in __import__("builtins").range(ph):
            for j in __import__("builtins").range(pw):
                ys = int(_np.floor(y1 + i * rh))
                ye = max(int(_np.ceil(y1 + (i + 1) * rh)), ys + 1)
                xs = int(_np.floor(x1 + j * rw))
                xe = max(int(_np.ceil(x1 + (j + 1) * rw)), xs + 1)
                ys, ye = _np.clip([ys, ye], 0, h)
                xs, xe = _np.clip([xs, xe], 0, w)
                if ye <= ys or xe <= xs:
                    continue
                for oc in __import__("builtins").range(output_channels):
                    ch = oc * ph * pw + i * pw + j
                    out[ri, oc, i, j] = x[bi, ch, ys:ye, xs:xe].mean()
    return paddle.to_tensor(out)


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    """reference: prroi_pool_op (precise RoI pooling — exact bilinear
    integral). roi_align with a dense sampling grid converges to the same
    integral; lowered that way here."""
    from ..vision.ops import roi_align as _impl

    if batch_roi_nums is None:  # single image: all rois belong to it
        batch_roi_nums = paddle.to_tensor(
            __import__("numpy").asarray([int(rois.shape[0])], "int32"))
    return _impl(input, rois, batch_roi_nums,
                 (pooled_height, pooled_width), spatial_scale,
                 sampling_ratio=4)


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True, out_val_if_empty=0):
    """reference: filter_by_instag_op — keep rows whose tag intersects
    filter_tag."""
    _eager_only("filter_by_instag")
    import numpy as _np

    x = _np.asarray(ins.numpy() if not hasattr(ins, "data") else
                    ins.data.numpy())
    tags = _np.asarray(ins_tag.numpy()).reshape(-1)
    want = set(_np.asarray(filter_tag.numpy()).reshape(-1).tolist())
    keep = _np.asarray([t in want for t in tags])
    idx = _np.nonzero(keep)[0]
    if idx.size == 0:
        out = _np.full((1, *x.shape[1:]), out_val_if_empty, x.dtype)
        return (paddle.to_tensor(out),
                paddle.to_tensor(_np.zeros(0, "int64")),
                paddle.to_tensor(_np.zeros(1, "int64")))
    return (paddle.to_tensor(x[idx]), paddle.to_tensor(idx.astype("int64")),
            paddle.to_tensor(_np.ones(len(idx), "int64")))


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multi-box head (reference: detection.py multi_box_head):
    per-feature-map loc/conf convs + prior boxes, concatenated."""
    import numpy as _np

    n_in = len(inputs)
    if min_sizes is None:
        # reference ratio schedule
        step = int(_np.floor((max_ratio - min_ratio) / (n_in - 2)))
        min_sizes, max_sizes = [], []
        for ratio in __import__("builtins").range(min_ratio, max_ratio + 1,
                                                  step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        mins = [min_sizes[i]] if not isinstance(min_sizes[i], list) \
            else min_sizes[i]
        maxs = [max_sizes[i]] if max_sizes else None
        ars = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                             (list, tuple)) \
            else [aspect_ratios[i]]
        st = steps[i] if steps else ((step_w[i] if step_w else 0.0),
                                     (step_h[i] if step_h else 0.0))
        box, var = prior_box(feat, image, mins, maxs, ars, list(variance),
                             flip, clip, st if isinstance(st, (list, tuple))
                             else (st, st), offset,
                             min_max_aspect_ratios_order=
                             min_max_aspect_ratios_order)
        n_priors_cell = int(box.shape[2])
        loc = conv2d(feat, n_priors_cell * 4, kernel_size, stride=stride,
                     padding=pad)
        conf = conv2d(feat, n_priors_cell * num_classes, kernel_size,
                      stride=stride, padding=pad)
        b = int(feat.shape[0])
        locs.append(paddle.reshape(
            paddle.transpose(loc, [0, 2, 3, 1]), [b, -1, 4]))
        confs.append(paddle.reshape(
            paddle.transpose(conf, [0, 2, 3, 1]), [b, -1, num_classes]))
        boxes_all.append(paddle.reshape(box, [-1, 4]))
        vars_all.append(paddle.reshape(var, [-1, 4]))
    return (paddle.concat(locs, 1), paddle.concat(confs, 1),
            paddle.concat(boxes_all, 0), paddle.concat(vars_all, 0))


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None):
    """reference: detection.py ssd_loss — match priors to gt, smooth-l1 loc
    loss on positives + softmax conf loss with hard negative mining."""
    _eager_only("ssd_loss")
    import numpy as _np

    iou = iou_similarity(gt_box, prior_box)  # [n_gt, n_prior]
    match_idx, _ = bipartite_match(iou, match_type, overlap_threshold)
    mi = _np.asarray(match_idx.numpy())  # per-prior gt index or -1
    pos = mi >= 0
    n_pos = max(int(pos.sum()), 1)

    gt_b = _np.asarray(gt_box.numpy())
    gt_l = _np.asarray(gt_label.numpy()).reshape(-1)
    pb = _np.asarray(prior_box.numpy())
    loc_np = _np.asarray(location.numpy())[0] if location.ndim == 3 \
        else _np.asarray(location.numpy())
    conf_np = confidence

    # encode matched gt against priors (center-size, like box_coder encode)
    target = _np.zeros_like(loc_np)
    pw = pb[:, 2] - pb[:, 0]
    ph = pb[:, 3] - pb[:, 1]
    px = (pb[:, 0] + pb[:, 2]) / 2
    py = (pb[:, 1] + pb[:, 3]) / 2
    var = _np.asarray(prior_box_var.numpy()) if prior_box_var is not None \
        else _np.ones_like(pb)
    for p in _np.nonzero(pos)[0]:
        g = gt_b[mi[p]]
        gx, gy = (g[0] + g[2]) / 2, (g[1] + g[3]) / 2
        gw, gh = max(g[2] - g[0], 1e-6), max(g[3] - g[1], 1e-6)
        target[p] = [(gx - px[p]) / pw[p] / var[p, 0],
                     (gy - py[p]) / ph[p] / var[p, 1],
                     _np.log(gw / pw[p]) / var[p, 2],
                     _np.log(gh / ph[p]) / var[p, 3]]

    loc_t = paddle.to_tensor(target.astype("float32"))
    loc_p = paddle.to_tensor(loc_np.astype("float32"))
    loc_l = paddle.sum(smooth_l1(loc_p, loc_t), axis=-1)
    pos_t = paddle.to_tensor(pos.astype("float32"))
    loc_loss = paddle.sum(paddle.multiply(loc_l, pos_t))

    # conf target: matched gt label on positives, background elsewhere
    conf_target = _np.full(mi.shape, background_label, "int64")
    conf_target[pos] = gt_l[mi[pos]]
    cf = conf_np if conf_np.ndim == 2 else paddle.squeeze(conf_np, [0])
    ce = F.cross_entropy(cf, paddle.to_tensor(conf_target),
                         reduction="none")
    ce_np = _np.asarray(ce.numpy())
    # hard negative mining: top neg_pos_ratio * n_pos negatives by loss
    neg_cand = _np.nonzero(~pos)[0]
    order = neg_cand[_np.argsort(-ce_np[neg_cand])]
    n_neg = min(int(neg_pos_ratio * n_pos), len(order))
    sel = _np.zeros_like(pos)
    sel[order[:n_neg]] = True
    conf_mask = paddle.to_tensor((pos | sel).astype("float32"))
    conf_loss = paddle.sum(paddle.multiply(ce, conf_mask))

    total = paddle.add(paddle.scale(loc_loss, scale=loc_loss_weight),
                       paddle.scale(conf_loss, scale=conf_loss_weight))
    if normalize:
        total = paddle.scale(total, scale=1.0 / n_pos)
    return total


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    """reference: yolov3_loss_op — per-cell objectness + box + class loss
    against assigned ground truths (compact dense formulation)."""
    _eager_only("yolov3_loss")
    import numpy as _np

    xv = _np.asarray(x.numpy())
    n, c, h, w = xv.shape
    na = len(anchor_mask)
    xv = xv.reshape(n, na, 5 + class_num, h, w)
    gt_b = _np.asarray(gt_box.numpy())      # [n, B, 4] cx,cy,w,h (normalized)
    gt_l = _np.asarray(gt_label.numpy())    # [n, B]
    masked_anchors = [(anchors[2 * m] / (downsample_ratio * w),
                       anchors[2 * m + 1] / (downsample_ratio * h))
                      for m in anchor_mask]

    obj_mask = _np.zeros((n, na, h, w), "float32")
    t_xywh = _np.zeros((n, na, 4, h, w), "float32")
    t_cls = _np.zeros((n, na, class_num, h, w), "float32")
    for b in __import__("builtins").range(n):
        for g in __import__("builtins").range(gt_b.shape[1]):
            gw, gh = gt_b[b, g, 2], gt_b[b, g, 3]
            if gw <= 0 or gh <= 0:
                continue
            gi = min(int(gt_b[b, g, 0] * w), w - 1)
            gj = min(int(gt_b[b, g, 1] * h), h - 1)
            # best anchor by shape IoU
            best, best_iou = 0, 0.0
            for ai, (aw, ah) in enumerate(masked_anchors):
                inter = min(gw * w, aw * w) * min(gh * h, ah * h)
                union = gw * w * gh * h + aw * w * ah * h - inter
                if inter / union > best_iou:
                    best, best_iou = ai, inter / union
            obj_mask[b, best, gj, gi] = 1.0
            aw, ah = masked_anchors[best]
            t_xywh[b, best, :, gj, gi] = [
                gt_b[b, g, 0] * w - gi, gt_b[b, g, 1] * h - gj,
                _np.log(max(gw / aw, 1e-9)), _np.log(max(gh / ah, 1e-9))]
            t_cls[b, best, int(gt_l[b, g]), gj, gi] = 1.0

    pred = paddle.to_tensor(xv.astype("float32"))
    om = paddle.to_tensor(obj_mask)
    txy = paddle.to_tensor(t_xywh[:, :, :2])
    twh = paddle.to_tensor(t_xywh[:, :, 2:])
    tc = paddle.to_tensor(t_cls)

    pxy = paddle.slice(pred, [2], [0], [2])
    pwh = paddle.slice(pred, [2], [2], [4])
    pobj = paddle.squeeze(paddle.slice(pred, [2], [4], [5]), [2])
    pcls = paddle.slice(pred, [2], [5], [5 + class_num])

    om4 = paddle.unsqueeze(om, 2)
    xy_l = paddle.sum(paddle.multiply(F.binary_cross_entropy_with_logits(
        pxy, txy, reduction="none"), om4))
    wh_l = paddle.sum(paddle.multiply(paddle.abs(
        paddle.subtract(pwh, twh)), om4))
    obj_l = paddle.sum(F.binary_cross_entropy_with_logits(
        pobj, om, reduction="none"))
    cls_l = paddle.sum(paddle.multiply(F.binary_cross_entropy_with_logits(
        pcls, tc, reduction="none"), om4))
    return paddle.add(paddle.add(xy_l, wh_l), paddle.add(obj_l, cls_l))


def retinanet_detection_output(bboxes, scores, im_info, score_threshold=0.05,
                               nms_top_k=1000, keep_top_k=100,
                               nms_threshold=0.3, nms_eta=1.0):
    """reference: retinanet_detection_output_op — concat FPN levels, then
    standard multiclass NMS."""
    all_boxes = paddle.concat(bboxes, axis=0) if isinstance(bboxes, (list, tuple)) else bboxes
    sc = paddle.concat(scores, axis=0) if isinstance(scores, (list, tuple)) else scores
    return multiclass_nms(all_boxes, paddle.transpose(sc, [1, 0]),
                          score_threshold, nms_top_k, keep_top_k,
                          nms_threshold, background_label=-1)


def _lod_era_gate(op_name, modern):
    raise NotImplementedError(
        f"{op_name} consumes LoD-era detection-training structures; "
        f"{modern}")


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info, **kwargs):
    _lod_era_gate("rpn_target_assign",
                  "compose iou_similarity + bipartite_match + target_assign "
                  "for anchor labeling on padded batches")


def retinanet_target_assign(*args, **kwargs):
    _lod_era_gate("retinanet_target_assign",
                  "compose iou_similarity + bipartite_match + target_assign")


def generate_proposal_labels(*args, **kwargs):
    _lod_era_gate("generate_proposal_labels",
                  "sample proposals hostside from generate_proposals output")


def generate_mask_labels(*args, **kwargs):
    _lod_era_gate("generate_mask_labels",
                  "crop gt masks hostside against sampled rois")


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, name=None):
    """reference: locality_aware_nms_op (EAST text) — row-adjacent weighted
    merge, then standard multiclass NMS."""
    _eager_only("locality_aware_nms")
    import numpy as _np

    b = _np.asarray(bboxes.numpy())
    s = _np.asarray(scores.numpy())
    merged_b, merged_s = [], []
    for i in __import__("builtins").range(b.shape[0]):
        if merged_b:
            last = merged_b[-1]
            xx1 = max(last[0], b[i, 0]); yy1 = max(last[1], b[i, 1])
            xx2 = min(last[2], b[i, 2]); yy2 = min(last[3], b[i, 3])
            inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
            a1 = (last[2] - last[0]) * (last[3] - last[1])
            a2 = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
            if inter / max(a1 + a2 - inter, 1e-9) > nms_threshold:
                w1, w2 = merged_s[-1], s[..., i].max()
                tot = max(w1 + w2, 1e-9)
                merged_b[-1] = (last * w1 + b[i] * w2) / tot
                merged_s[-1] = max(w1, w2)
                continue
        merged_b.append(b[i].astype("float64"))
        merged_s.append(float(s[..., i].max()))
    mb = paddle.to_tensor(_np.asarray(merged_b, "float32"))
    ms = paddle.to_tensor(
        _np.broadcast_to(_np.asarray(merged_s, "float32"),
                         (s.shape[0], len(merged_s))).copy())
    return multiclass_nms(mb, ms, score_threshold, nms_top_k, keep_top_k,
                          nms_threshold, background_label=-1)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    """reference: roi_perspective_transform_op (OCR/EAST) — warp each quad
    ROI (8 coords x1..y4, clockwise from top-left) to a transformed_height x
    transformed_width rectangle. Per-ROI homography by 4-point DLT solve
    (jnp.linalg.solve, differentiable), then bilinear sampling; single
    feature-map batch (the LoD roi->image map has no static-shape analog).
    Returns (out, mask, transform_matrix) like the reference."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import primitive_call as _pc

    th, tw = int(transformed_height), int(transformed_width)

    def f(x, quads):
        if x.shape[0] != 1:
            raise ValueError(
                "roi_perspective_transform: batch>1 needs the LoD roi->image "
                "map; run per image")
        H, W = x.shape[2], x.shape[3]
        q = quads.reshape(-1, 4, 2) * spatial_scale  # [R, 4, (x,y)]
        # destination rectangle corners (same order as the reference op)
        dst = jnp.asarray([[0.0, 0.0], [tw - 1.0, 0.0],
                           [tw - 1.0, th - 1.0], [0.0, th - 1.0]])

        def homography(src4):
            # DLT: solve the 8x8 system for H mapping dst -> src
            rows = []
            for k in __import__("builtins").range(4):
                X, Y = dst[k, 0], dst[k, 1]
                u, v = src4[k, 0], src4[k, 1]
                rows.append(jnp.stack([X, Y, 1.0, 0.0, 0.0, 0.0,
                                       -u * X, -u * Y]))
                rows.append(jnp.stack([0.0, 0.0, 0.0, X, Y, 1.0,
                                       -v * X, -v * Y]))
            A = jnp.stack(rows)
            b = src4.reshape(-1)
            h8 = jnp.linalg.solve(A, b)
            return jnp.concatenate([h8, jnp.ones(1)]).reshape(3, 3)

        Hs = jax.vmap(homography)(q)  # [R, 3, 3]
        yy, xx = jnp.meshgrid(jnp.arange(th, dtype=jnp.float32),
                              jnp.arange(tw, dtype=jnp.float32),
                              indexing="ij")
        ones = jnp.ones_like(xx)
        grid = jnp.stack([xx, yy, ones], axis=-1).reshape(-1, 3)  # [th*tw, 3]

        def warp_one(Hm):
            src = grid @ Hm.T  # [th*tw, 3]
            sx = src[:, 0] / src[:, 2]
            sy = src[:, 1] / src[:, 2]
            inb = (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
            x0 = jnp.clip(jnp.floor(sx), 0, W - 1)
            y0 = jnp.clip(jnp.floor(sy), 0, H - 1)
            x1 = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            y1 = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
            wx, wy = sx - x0, sy - y0
            img = x[0]  # [C, H, W]
            v = (img[:, y0i, x0i] * (1 - wy) * (1 - wx)
                 + img[:, y0i, x1] * (1 - wy) * wx
                 + img[:, y1, x0i] * wy * (1 - wx)
                 + img[:, y1, x1] * wy * wx)
            v = jnp.where(inb[None, :], v, 0.0)
            return v.reshape(-1, th, tw), inb.reshape(th, tw)

        out, mask = jax.vmap(warp_one)(Hs)
        return out, mask.astype(jnp.int32)[:, None], Hs

    return _pc(f, input, rois, name="roi_perspective_transform")


def deformable_roi_pooling(input, rois, trans, **kwargs):
    _lod_era_gate("deformable_roi_pooling",
                  "use vision.ops.deform_conv2d + roi_align")


def similarity_focus(input, axis, indexes, name=None):
    """reference: similarity_focus_op — binary focus mask marking, per
    (batch, selected channel), the argmax positions across the remaining
    axes."""
    _eager_only("similarity_focus")
    import numpy as _np

    x = _np.asarray(input.numpy())
    out = _np.zeros_like(x)
    n = x.shape[0]
    for b in __import__("builtins").range(n):
        for ch in indexes:
            m = x[b, ch] if axis == 1 else _np.take(x[b], ch, axis=axis - 1)
            # mark row/col argmax pattern (reference: per-row and per-col max)
            rows = m.argmax(1)
            cols = m.argmax(0)
            mask = _np.zeros_like(m, dtype=bool)
            mask[_np.arange(m.shape[0]), rows] = True
            mask[cols, _np.arange(m.shape[1])] = True
            if axis == 1:
                out[b, :, mask] = 1.0
            else:
                out[b][..., mask] = 1.0
    return paddle.to_tensor(out)


def reorder_lod_tensor_by_rank(x, rank_table):
    _lod_era_gate("reorder_lod_tensor_by_rank",
                  "sort padded batches by length hostside "
                  "(io/batch.py bucketing)")
