"""fluid.layers — 1.x layer-function aliases (reference fluid/layers/*).

Ops keep their fluid argument spellings (dim/keep_dim, pool_type, act=...)
and delegate to the 2.x lowerings.
"""
from __future__ import annotations

import paddle_tpu as paddle
from .. import nn as _nn
from ..nn import functional as F
from ..static import data as _static_data
from ..static.nn import (  # noqa: F401
    batch_norm,
    conv2d,
    conv2d_transpose,
    conv3d,
    crf_decoding,
    embedding,
    fc as _fc,
    group_norm,
    instance_norm,
    layer_norm,
    nce,
    prelu,
    row_conv,
    sequence_concat,
    sequence_conv,
    sequence_enumerate,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_reverse,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
    sparse_embedding,
)

# direct re-exports where 1.x and 2.x agree
concat = paddle.concat
reshape = paddle.reshape
transpose = paddle.transpose
cast = paddle.cast
assign = paddle.assign
shape = paddle.shape
zeros = paddle.zeros
ones = paddle.ones
relu = F.relu
sigmoid = F.sigmoid
tanh = paddle.tanh
softmax = F.softmax
softmax_with_cross_entropy = F.softmax_with_cross_entropy
square = paddle.square
sqrt = paddle.sqrt
abs = paddle.abs  # noqa: A001 — fluid spelling
log = paddle.log
exp = paddle.exp
clip = paddle.clip
stack = paddle.stack
gather = paddle.gather
scatter = paddle.scatter
one_hot = F.one_hot
label_smooth = F.label_smooth
sequence_mask = F.sequence_mask


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    """fluid semantics: `input` is PROBABILITIES (softmax already applied)
    and the result is the PER-EXAMPLE loss [N, 1] — not 2.x's
    logits+mean-reduce (fluid/layers/loss.py cross_entropy)."""
    out = F.cross_entropy(input, label, soft_label=soft_label,
                          ignore_index=ignore_index, use_softmax=False,
                          reduction="none")
    return paddle.unsqueeze(out, -1) if len(out.shape) == 1 else out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    """fluid semantics: default downgrade_in_infer — kept values UNSCALED
    at train time, activations scaled by (1-p) at inference."""
    if is_test:
        if dropout_implementation == "downgrade_in_infer":
            return x * (1.0 - dropout_prob)
        return x
    return F.dropout(x, p=dropout_prob, training=True,
                     mode="upscale_in_train"
                     if dropout_implementation == "upscale_in_train"
                     else "downgrade_in_infer")


def expand(x, expand_times, name=None):
    """fluid expand == TILE by repeat counts (2.x renamed it paddle.tile;
    paddle.expand broadcasts to a target shape — different op)."""
    return paddle.tile(x, expand_times)


def split(input, num_or_sections, dim=-1, name=None):
    """fluid default splits the LAST dim and spells the axis `dim`."""
    return paddle.split(input, num_or_sections, axis=dim)


def data(name, shape, dtype="float32", append_batch_size=True, lod_level=0,
         type=None, stop_gradient=True):
    """fluid.layers.data: 1.x semantics prepend an implicit -1 batch dim
    (fluid.data / 2.x static.data do NOT — that alias lives at the fluid
    package root)."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return _static_data(name, shape, dtype)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """fluid spelling (act=/param_attr=) over static.nn.fc."""
    return _fc(input, size, num_flatten_dims=num_flatten_dims,
               weight_attr=param_attr, bias_attr=bias_attr, activation=act,
               name=name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """reference mul_op: flatten x to 2-D at x_num_col_dims and y at
    y_num_col_dims, matmul, restore x.shape[:xd] + y.shape[yd:]."""
    import numpy as np

    xs, ys = list(x.shape), list(y.shape)
    xm = paddle.reshape(x, [int(np.prod(xs[:x_num_col_dims]) or 1),
                            int(np.prod(xs[x_num_col_dims:]) or 1)])
    ym = paddle.reshape(y, [int(np.prod(ys[:y_num_col_dims]) or 1),
                            int(np.prod(ys[y_num_col_dims:]) or 1)])
    out = paddle.matmul(xm, ym)
    return paddle.reshape(out, xs[:x_num_col_dims] + ys[y_num_col_dims:])


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    out = paddle.matmul(x, y, transpose_x=transpose_x,
                        transpose_y=transpose_y)
    return out * alpha if alpha != 1.0 else out


def mean(x, name=None):
    return paddle.mean(x)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return paddle.mean(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return paddle.sum(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return paddle.max(input, axis=dim, keepdim=keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return paddle.min(input, axis=dim, keepdim=keep_dim)


def _align_y(x, y, axis):
    """fluid mid-axis broadcasting: y's dims align with x STARTING AT
    `axis` (elementwise_op semantics) — append trailing 1-dims so numpy
    broadcasting reproduces it."""
    if axis == -1 or not hasattr(y, "shape"):
        return y
    trailing = len(x.shape) - axis - len(y.shape)
    if trailing <= 0:
        return y
    return paddle.reshape(y, list(y.shape) + [1] * trailing)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.add(x, _align_y(x, y, axis)), act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.subtract(x, _align_y(x, y, axis)), act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.multiply(x, _align_y(x, y, axis)), act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.divide(x, _align_y(x, y, axis)), act)


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    return paddle.full(shape, value, dtype=dtype)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           name=None, data_format="NCHW"):
    if global_pooling:
        if pool_type == "max":
            return F.adaptive_max_pool2d(input, 1)
        return F.adaptive_avg_pool2d(input, 1)
    if pool_type == "max":
        return F.max_pool2d(input, pool_size, stride=pool_stride,
                            padding=pool_padding, ceil_mode=ceil_mode)
    return F.avg_pool2d(input, pool_size, stride=pool_stride,
                        padding=pool_padding, ceil_mode=ceil_mode)


def flatten(x, axis=1, name=None):
    """fluid flatten: ALWAYS 2-D — [prod(shape[:axis]), prod(shape[axis:])]
    (2.x flatten(start_axis, stop_axis) is a different op)."""
    import numpy as np

    xs = list(x.shape)
    # np.prod([]) == 1.0, and zero-size dims must stay 0 — no `or 1` fixups
    return paddle.reshape(x, [int(np.prod(xs[:axis])),
                              int(np.prod(xs[axis:]))])


def topk(input, k, name=None):
    return paddle.topk(input, k)  # last dim, values+indices (same in 1.x)


def argmax(x, axis=0, name=None):
    return paddle.argmax(x, axis=axis)  # 1.x default axis=0 (2.x flattens)


def argmin(x, axis=0, name=None):
    return paddle.argmin(x, axis=axis)


def squeeze(input, axes, name=None):
    # fluid: empty axes means squeeze EVERY size-1 dim
    return paddle.squeeze(input, axis=axes if axes else None)


def unsqueeze(input, axes, name=None):
    return paddle.unsqueeze(input, axis=axes)


def pad(x, paddings, pad_value=0.0, name=None):
    """fluid pad: flat [before0, after0, before1, after1, ...] list."""
    return paddle.nn.functional.pad(
        x, paddings, value=pad_value, mode="constant")


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    return paddle.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    if seed:  # seeded draws must be reproducible (paddle.normal has no seed)
        import jax
        import jax.numpy as jnp

        arr = mean + std * jax.random.normal(
            jax.random.key(seed), tuple(int(s) for s in shape))
        return paddle.to_tensor(arr.astype(jnp.dtype(dtype)))
    return paddle.normal(mean=mean, std=std, shape=shape).astype(dtype)


def _maybe_act(out, act):
    if act is None:
        return out
    return getattr(F, act)(out)


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


# --------------------------------------------------------------- batch 3
# (reference fluid/layers/{nn,tensor,ops,loss,control_flow,detection,
# learning_rate_scheduler,sequence_lod,rnn}.py — the long tail of 1.x
# names, each keeping its fluid spelling and delegating to 2.x lowerings)

# ---- activations / simple math
def leaky_relu(x, alpha=0.02, name=None):
    return F.leaky_relu(x, negative_slope=alpha)


def elu(x, alpha=1.0, name=None):
    return F.elu(x, alpha=alpha)


def relu6(x, threshold=6.0, name=None):
    # fluid's threshold arg is honored (2.x relu6 hardcodes 6)
    return paddle.clip(x, 0.0, threshold)


def selu(x, scale=None, alpha=None, name=None):
    kw = {}
    if scale is not None:
        kw["scale"] = scale
    if alpha is not None:
        kw["alpha"] = alpha
    return F.selu(x, **kw)


def mish(x, threshold=20, name=None):
    # softplus with the fluid threshold cutoff: x > threshold passes through
    sp = paddle.where(
        paddle.greater_than(x, paddle.full([], float(threshold), "float32")),
        x, F.softplus(x))
    return paddle.multiply(x, paddle.tanh(sp))


def swish(x, beta=1.0, name=None):
    return paddle.multiply(x, F.sigmoid(paddle.scale(x, scale=beta)))


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    # honor fluid's threshold/scale/offset (2.x hardswish fixes 6/6/3)
    return paddle.multiply(
        x, paddle.scale(paddle.clip(paddle.scale(x, bias=offset),
                                    0.0, threshold), scale=1.0 / scale))


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return F.hardsigmoid(x, slope=slope, offset=offset)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return paddle.clip(x, t_min, t_max)


def soft_relu(x, threshold=40.0, name=None):
    return paddle.log(paddle.scale(paddle.exp(paddle.clip(
        x, -threshold, threshold)), bias=1.0))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return paddle.stanh(x, scale_a=scale_a, scale_b=scale_b)


def maxout(x, groups, name=None, axis=1):
    return F.maxout(x, groups, axis=axis)


def pow(x, factor=1.0, name=None):  # noqa: A001
    return paddle.pow(x, factor)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.maximum(x, y), act)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.minimum(x, y), act)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.mod(x, y), act)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.floor_divide(x, y), act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.pow(x, y), act)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return F.normalize(x, p=2, axis=axis, epsilon=epsilon)


def cos_sim(X, Y):
    out = F.cosine_similarity(X, Y, axis=1)
    return paddle.reshape(out, [-1, 1])


def clip_by_norm(x, max_norm, name=None):
    norm = paddle.sqrt(paddle.sum(paddle.multiply(x, x)))
    factor = paddle.minimum(
        paddle.full([], 1.0, "float32"),
        paddle.divide(paddle.full([], float(max_norm), "float32"),
                      paddle.maximum(norm, paddle.full([], 1e-12, "float32"))))
    return paddle.multiply(x, factor)


def sign(x, name=None):
    return paddle.sign(x)


# ---- reductions / logic / comparison
def reduce_all(input, dim=None, keep_dim=False, name=None):
    return paddle.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return paddle.any(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return paddle.prod(input, axis=dim, keepdim=keep_dim)


def equal(x, y, cond=None, name=None):
    return paddle.equal(x, y)


def not_equal(x, y, cond=None, name=None):
    return paddle.not_equal(x, y)


def greater_than(x, y, cond=None, name=None):
    return paddle.greater_than(x, y)


def greater_equal(x, y, cond=None, name=None):
    return paddle.greater_equal(x, y)


def less_than(x, y, force_cpu=None, cond=None, name=None):
    return paddle.less_than(x, y)


def less_equal(x, y, cond=None, name=None):
    return paddle.less_equal(x, y)


def logical_and(x, y, out=None, name=None):
    return paddle.logical_and(x, y)


def logical_or(x, y, out=None, name=None):
    return paddle.logical_or(x, y)


def logical_xor(x, y, out=None, name=None):
    return paddle.logical_xor(x, y)


def logical_not(x, out=None, name=None):
    return paddle.logical_not(x)


def is_empty(x, name=None):
    return paddle.to_tensor(bool(int(paddle.numel(x).numpy()) == 0)) \
        if not paddle.in_dynamic_mode() is False else \
        paddle.equal(paddle.numel(x), paddle.full([], 0, "int64"))


def isfinite(x, name=None):
    return paddle.all(paddle.isfinite(x))


def has_inf(x):
    return paddle.any(paddle.isinf(x))


def has_nan(x):
    return paddle.any(paddle.isnan(x))


# ---- tensor creation / manipulation
def create_tensor(dtype, name=None, persistable=False):
    return paddle.to_tensor(__import__("numpy").zeros((), dtype))


def argsort(input, axis=-1, descending=False, name=None):
    ids = paddle.argsort(input, axis=axis, descending=descending)
    vals = paddle.sort(input, axis=axis, descending=descending)
    return vals, ids


def linspace(start, stop, num, dtype="float32", name=None):
    return paddle.linspace(start, stop, num, dtype=dtype)


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32",
        name=None):
    out = paddle.eye(num_rows, num_columns, dtype=dtype)
    if batch_shape:
        for _ in batch_shape:
            out = paddle.unsqueeze(out, 0)
        out = paddle.expand(out, list(batch_shape) + list(out.shape[-2:]))
    return out


def ones_like(x, out=None, name=None):
    return paddle.ones_like(x)


def zeros_like(x, out=None, name=None):
    return paddle.zeros_like(x)


def diag(diagonal, name=None):
    return paddle.diag(diagonal)


def triu(input, diagonal=0, name=None):
    return paddle.triu(input, diagonal)


def range(start, end, step, dtype, name=None):  # noqa: A001
    return paddle.arange(start, end, step, dtype)


def reverse(x, axis, name=None):
    return paddle.flip(x, axis if isinstance(axis, (list, tuple)) else [axis])


def multiplex(inputs, index, name=None):
    return paddle.multiplex(inputs, index)


def strided_slice(input, axes, starts, ends, strides, name=None):
    return paddle.strided_slice(input, axes, starts, ends, strides)


def slice(input, axes, starts, ends, name=None):  # noqa: A001
    return paddle.slice(input, axes, starts, ends)


def crop_tensor(x, shape=None, offsets=None, name=None):
    return paddle.crop(x, shape=shape, offsets=offsets)


def crop(x, shape=None, offsets=None, name=None):
    return paddle.crop(x, shape=shape, offsets=offsets)


def expand_as(x, target_tensor, name=None):
    return paddle.expand_as(x, target_tensor)


def gather_nd(input, index, name=None):
    return paddle.gather_nd(input, index)


def scatter_nd(index, updates, shape, name=None):
    return paddle.scatter_nd(index, updates, shape)


def scatter_nd_add(ref, index, updates, name=None):
    return paddle.scatter_nd_add(ref, index, updates)


def unstack(x, axis=0, num=None):
    return paddle.unstack(x, axis=axis, num=num)


def unbind(input, axis=0):
    return paddle.unbind(input, axis=axis)


def unique(x, dtype="int32"):
    out, index = paddle.unique(x, return_index=True)
    return out, paddle.cast(index, dtype)


def unique_with_counts(x, dtype="int32"):
    out, index, counts = paddle.unique(x, return_index=True,
                                       return_counts=True)
    return out, paddle.cast(index, dtype), paddle.cast(counts, dtype)


def increment(x, value=1.0, in_place=True):
    out = paddle.scale(x, bias=float(value))
    if in_place and hasattr(x, "_value"):
        x._value = out._value
        return x
    return out


def rank(input):
    return paddle.rank(input)


def size(input):
    return paddle.numel(input)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return paddle.shard_index(input, index_num, nshards, shard_id,
                              ignore_value)


def sums(input, out=None):
    total = input[0]
    for t in input[1:]:
        total = paddle.add(total, t)
    return total


def sum(x):  # noqa: A001
    if isinstance(x, (list, tuple)):
        return sums(x)
    return paddle.sum(x)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return F.pad(input, list(paddings), mode=mode.replace("edge", "replicate"),
                 value=pad_value, data_format=data_format)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    pads = []
    for xs, ys in zip(x.shape, y.shape):
        pads += [0, int(xs) - int(ys)]
    return F.pad(y, pads, value=pad_value)


def space_to_depth(x, blocksize, name=None):
    return F.pixel_unshuffle(x, blocksize)


def shuffle_channel(x, group, name=None):
    return F.channel_shuffle(x, group)


def pixel_shuffle(x, upscale_factor):
    return F.pixel_shuffle(x, upscale_factor)


def fsp_matrix(x, y):
    b, cx = x.shape[0], x.shape[1]
    cy = y.shape[1]
    h, w = x.shape[2], x.shape[3]
    xf = paddle.reshape(x, [b, cx, -1])
    yf = paddle.reshape(y, [b, cy, -1])
    return paddle.scale(paddle.matmul(xf, paddle.transpose(yf, [0, 2, 1])),
                        scale=1.0 / float(int(h) * int(w)))


def add_position_encoding(input, alpha, beta, name=None):
    import numpy as _np

    b, s, d = (int(v) for v in input.shape)
    pos = _np.arange(s, dtype="float32")[:, None]
    half = d // 2
    div = _np.power(10000.0, -_np.arange(half, dtype="float32") / half)
    enc = _np.zeros((s, d), "float32")
    enc[:, :half] = _np.sin(pos * div)
    enc[:, half:2 * half] = _np.cos(pos * div)
    return paddle.add(paddle.scale(input, scale=alpha),
                      paddle.scale(paddle.to_tensor(enc), scale=beta))


# ---- losses
def mse_loss(input, label):
    return F.mse_loss(input, label)


def square_error_cost(input, label):
    return F.square_error_cost(input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    return F.log_loss(input, label, epsilon)


def kldiv_loss(x, target, reduction="mean", name=None):
    return F.kl_div(x, target, reduction=reduction)


def huber_loss(input, label, delta):
    diff = paddle.subtract(input, label)
    abs_diff = paddle.abs(diff)
    quad = paddle.scale(paddle.multiply(diff, diff), scale=0.5)
    lin = paddle.scale(paddle.subtract(abs_diff,
                                       paddle.full([], delta / 2.0,
                                                   "float32")), scale=delta)
    return paddle.where(paddle.less_equal(
        abs_diff, paddle.full([], float(delta), "float32")), quad, lin)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    diff = paddle.subtract(x, y)
    if inside_weight is not None:
        diff = paddle.multiply(diff, inside_weight)
    sigma2 = (sigma if sigma is not None else 1.0) ** 2
    abs_diff = paddle.abs(diff)
    thresh = paddle.full([], 1.0 / sigma2, "float32")
    quad = paddle.scale(paddle.multiply(diff, diff), scale=0.5 * sigma2)
    lin = paddle.subtract(abs_diff, paddle.full([], 0.5 / sigma2, "float32"))
    out = paddle.where(paddle.less_than(abs_diff, thresh), quad, lin)
    if outside_weight is not None:
        out = paddle.multiply(out, outside_weight)
    return paddle.sum(out, axis=-1, keepdim=True)


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    loss = F.binary_cross_entropy_with_logits(x, label, reduction="none")
    mask = paddle.cast(paddle.not_equal(
        label, paddle.full([], float(ignore_index), label.dtype)), x.dtype)
    loss = paddle.multiply(loss, mask)
    if normalize:
        loss = paddle.divide(loss, paddle.maximum(
            paddle.sum(mask), paddle.full([], 1.0, x.dtype)))
    return loss


def bpr_loss(input, label, name=None):
    """Bayesian pairwise ranking (reference: fluid/layers/loss.py bpr_loss):
    mean over the C-1 NEGATIVE classes of -log(sigmoid(pos - neg))."""
    n_class = int(input.shape[-1])
    onehot = F.one_hot(paddle.reshape(label, [-1]), n_class)
    pos = paddle.sum(paddle.multiply(input, onehot), axis=-1, keepdim=True)
    diff = paddle.subtract(input, pos)
    loss = paddle.scale(paddle.log(paddle.scale(
        F.sigmoid(paddle.scale(diff, scale=-1.0)), bias=1e-8)), scale=-1.0)
    # exclude the positive column from the average (divisor C-1)
    neg_mask = paddle.scale(onehot, scale=-1.0, bias=1.0)
    total = paddle.sum(paddle.multiply(loss, neg_mask), axis=-1, keepdim=True)
    return paddle.scale(total, scale=1.0 / max(n_class - 1, 1))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return F.npair_loss(anchor, positive, labels, l2_reg)


def rank_loss(label, left, right, name=None):
    out = paddle.subtract(left, right)
    return paddle.add(
        paddle.subtract(F.softplus(out), paddle.multiply(label, out)),
        paddle.zeros_like(out))


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return F.margin_ranking_loss(left, right, label, margin=margin,
                                 reduction="none")


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference: fluid/layers/loss.py teacher_student_sigmoid_loss —
    z = clip(x); loss = log(1+exp(-|z|)) + max(z,0) - z*label."""
    z = paddle.clip(input, soft_max_lower_bound, soft_max_up_bound)
    return paddle.subtract(
        paddle.add(F.softplus(paddle.scale(paddle.abs(z), scale=-1.0)),
                   paddle.maximum(z, paddle.zeros_like(z))),
        paddle.multiply(z, label))


def dice_loss(input, label, epsilon=1e-5):
    return F.dice_loss(input, label, epsilon)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    return F.sigmoid_focal_loss(x, label, normalizer=fg_num, alpha=alpha,
                                gamma=gamma, reduction="none")


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """reference: fluid/layers/loss.py center_loss — distance to a running
    class-center table (the table updates eagerly like BN stats)."""
    import numpy as _np

    key = "_center_loss_centers_%d_%d" % (num_classes, int(input.shape[-1]))
    store = center_loss.__dict__.setdefault("tables", {})
    if key not in store:
        store[key] = paddle.to_tensor(
            _np.zeros((num_classes, int(input.shape[-1])), "float32"))
    centers = store[key]
    picked = F.embedding(paddle.reshape(label, [-1]), centers)
    diff = paddle.subtract(input, picked)
    loss = paddle.scale(paddle.sum(paddle.multiply(diff, diff),
                                   axis=-1, keepdim=True), scale=0.5)
    if update_center and paddle.in_dynamic_mode():
        import jax.numpy as _jnp

        lv = _np.asarray(paddle.reshape(label, [-1]).numpy())
        dv = _np.asarray(diff.numpy())
        counts = _np.bincount(lv, minlength=num_classes)[:, None] + 1.0
        upd = _np.zeros(centers.shape, "float32")
        _np.add.at(upd, lv, dv)
        centers._value = centers._value + _jnp.asarray(
            alpha * upd / counts)
    return loss


# ---- resize family
def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "LINEAR": "linear",
            "BICUBIC": "bicubic"}[resample.upper()]
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode=mode, align_corners=align_corners,
                         data_format=data_format)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode, data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True, data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners, 1, data_format)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format="NCW"):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode="linear", align_corners=align_corners,
                         data_format=data_format)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode="trilinear", align_corners=align_corners,
                         data_format=data_format)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    ratio = out_short_len / float(short)
    return image_resize(input, [int(round(h * ratio)), int(round(w * ratio))],
                        resample=resample)


# ---- vision extras
def grid_sampler(x, grid, name=None):
    return F.grid_sample(x, grid)


def affine_grid(theta, out_shape, name=None):
    return F.affine_grid(theta, out_shape)


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    shape = [1, -1, 1, 1] if data_layout == "NCHW" else [1, 1, 1, -1]
    out = x
    if scale is not None:
        out = paddle.multiply(out, paddle.reshape(scale, shape))
    if bias is not None:
        out = paddle.add(out, paddle.reshape(bias, shape))
    return _maybe_act(out, act)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return F.temporal_shift(x, seg_num, shift_ratio)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return F.unfold(x, kernel_sizes, strides, paddings, dilations)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    cols = F.unfold(input, filter_size, stride, padding)
    return paddle.transpose(cols, [0, 2, 1])


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    return F.local_response_norm(input, size=n, alpha=alpha * n, beta=beta,
                                 k=k, data_format=data_format)


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if pool_type == "max":
        return F.adaptive_max_pool2d(input, pool_size,
                                     return_mask=require_index)
    return F.adaptive_avg_pool2d(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if pool_type == "max":
        return F.adaptive_max_pool3d(input, pool_size,
                                     return_mask=require_index)
    return F.adaptive_avg_pool3d(input, pool_size)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCDHW"):
    if global_pooling:
        pool_size = [int(s) for s in input.shape[2:]]
        pool_padding = 0
    if pool_type == "max":
        return F.max_pool3d(input, pool_size, pool_stride, pool_padding,
                            ceil_mode=ceil_mode, data_format=data_format)
    return F.avg_pool3d(input, pool_size, pool_stride, pool_padding,
                        ceil_mode=ceil_mode, exclusive=exclusive,
                        data_format=data_format)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    layer = _nn.Conv3DTranspose(
        int(input.shape[1]), num_filters, filter_size, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr, data_format=data_format)
    return _maybe_act(layer(input), act)


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    layer = _nn.Bilinear(int(x.shape[-1]), int(y.shape[-1]), size)
    return _maybe_act(layer(x, y), act)


# ---- detection (vision/ops lowerings)
def iou_similarity(x, y, box_normalized=True, name=None):
    from ..vision.ops import iou_similarity as _impl

    return _impl(x, y, box_normalized)


def box_clip(input, im_info, name=None):
    from ..vision.ops import box_clip as _impl

    return _impl(input, im_info)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    from ..vision.ops import prior_box as _impl

    return _impl(input, image, min_sizes, max_sizes, aspect_ratios, variance,
                 flip, clip, steps, offset, min_max_aspect_ratios_order)


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    from ..vision.ops import anchor_generator as _impl

    return _impl(input, anchor_sizes, aspect_ratios, variance, stride, offset)


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    from ..vision.ops import bipartite_match as _impl

    return _impl(dist_matrix, match_type, dist_threshold)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    from ..vision.ops import multiclass_nms as _impl

    return _impl(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                 nms_threshold, normalized, nms_eta, background_label)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0):
    from ..vision.ops import yolo_box as _impl

    return _impl(x, img_size, anchors, class_num, conf_thresh,
                 downsample_ratio, clip_bbox, scale_x_y=scale_x_y)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    from ..vision.ops import box_coder as _impl

    return _impl(prior_box, prior_box_var, target_box, code_type,
                 box_normalized, axis=axis)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_num=None):
    from ..vision.ops import roi_align as _impl

    return _impl(input, rois, rois_num, (pooled_height, pooled_width),
                 spatial_scale, sampling_ratio)


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_num=None, name=None):
    from ..vision.ops import roi_pool as _impl

    return _impl(input, rois, rois_num, (pooled_height, pooled_width),
                 spatial_scale)


# ---- learning-rate decay (fluid functions → 2.x LRScheduler objects; the
# reference migration guide maps them the same way)
def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    return paddle.optimizer.lr.NoamDecay(d_model, warmup_steps, learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    if staircase:
        return paddle.optimizer.lr.StepDecay(learning_rate, decay_steps,
                                             decay_rate)
    return paddle.optimizer.lr.ExponentialDecay(
        learning_rate, decay_rate ** (1.0 / decay_steps))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    import math as _math

    if staircase:
        return paddle.optimizer.lr.StepDecay(
            learning_rate, decay_steps, _math.exp(-decay_rate))
    return paddle.optimizer.lr.ExponentialDecay(
        learning_rate, _math.exp(-decay_rate / decay_steps))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    return paddle.optimizer.lr.InverseTimeDecay(
        learning_rate, decay_rate / decay_steps)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    return paddle.optimizer.lr.PolynomialDecay(
        learning_rate, decay_steps, end_learning_rate, power, cycle)


def piecewise_decay(boundaries, values):
    return paddle.optimizer.lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    return paddle.optimizer.lr.CosineAnnealingDecay(
        learning_rate, step_each_epoch * epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    return paddle.optimizer.lr.LinearWarmup(learning_rate, warmup_steps,
                                            start_lr, end_lr)


# ---- control flow / arrays / misc
def while_loop(cond, body, loop_vars, is_test=False, name=None):
    from ..static import while_loop as _impl

    return _impl(cond, body, loop_vars)


def cond(pred, true_fn=None, false_fn=None, name=None):
    from ..static import cond as _impl

    return _impl(pred, true_fn, false_fn)


def case(pred_fn_pairs, default=None, name=None):
    from ..static import case as _impl

    return _impl(pred_fn_pairs, default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    from ..static import switch_case as _impl

    return _impl(branch_index, branch_fns, default)


def create_array(dtype):
    return []


def array_write(x, i, array=None):
    if array is None:
        array = []
    idx = int(i.numpy()) if hasattr(i, "numpy") else int(i)
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    idx = int(i.numpy()) if hasattr(i, "numpy") else int(i)
    return array[idx]


def array_length(array):
    return paddle.to_tensor(__import__("numpy").int64(len(array)))


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    items = [t for t in input if t is not None]
    out = paddle.stack(items, axis=axis) if use_stack \
        else paddle.concat(items, axis=axis)
    sizes = paddle.to_tensor(__import__("numpy").asarray(
        [int(t.shape[axis]) if not use_stack else 1 for t in items], "int32"))
    return out, sizes


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    store = autoincreased_step_counter.__dict__.setdefault("counters", {})
    key = counter_name or "@STEP_COUNTER@"
    val = store.get(key, begin - step) + step
    store[key] = val
    return paddle.to_tensor(__import__("numpy").int64(val))


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):  # noqa: A002
    import numpy as _np

    probs = _np.asarray(x.numpy(), "float64")
    rng = _np.random.RandomState(seed if seed else None)
    ids = [rng.choice(probs.shape[1], p=row / row.sum()) for row in probs]
    return paddle.to_tensor(_np.asarray(ids, "int64"))


def Assert(cond, data=None, summarize=20, name=None):
    import numpy as _np

    ok = bool(_np.all(_np.asarray(cond.numpy()))) if hasattr(cond, "numpy") \
        else bool(cond)
    if not ok:
        raise ValueError(
            f"Assert failed: {[_np.asarray(d.numpy())[:summarize] for d in (data or [])]}")
    return cond


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    from ..static.extras import py_func as _impl

    return _impl(func, x, out, backward_func)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance per pair (reference:
    fluid/layers/nn.py edit_distance → edit_distance_op). Host computation —
    the op is inherently data-dependent-loop shaped."""
    import numpy as _np
    from builtins import range as _range  # module-level `range` shadows it

    a = _np.asarray(input.numpy())
    b = _np.asarray(label.numpy())
    n = a.shape[0]
    dists = _np.zeros((n, 1), "float32")
    seq_num = paddle.to_tensor(_np.int64(n))
    for k in _range(n):
        s = a[k][: int(input_length.numpy()[k])] if input_length is not None \
            else a[k]
        t = b[k][: int(label_length.numpy()[k])] if label_length is not None \
            else b[k]
        if ignored_tokens:
            s = [v for v in s if v not in ignored_tokens]
            t = [v for v in t if v not in ignored_tokens]
        m, l = len(s), len(t)
        dp = _np.arange(l + 1, dtype="float32")
        for i in _range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in _range(1, l + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (s[i - 1] != t[j - 1]))
        d = dp[l]
        dists[k, 0] = d / max(l, 1) if normalized else d
    return paddle.to_tensor(dists), seq_num


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    return F.ctc_loss(input, label, input_length, label_length, blank=blank,
                      reduction="none")


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    import numpy as _np

    probs = _np.asarray(input.numpy())
    ids = probs.argmax(-1)  # [B, T] or [T, B]? fluid uses [T*B, C] LoD; take batch-major
    if ids.ndim == 1:
        ids = ids[None]
    outs = []
    lens = []
    for row in ids:
        dedup = [int(v) for i, v in enumerate(row)
                 if v != blank and (i == 0 or v != row[i - 1])]
        outs.append(dedup)
        lens.append(len(dedup))
    width = max(1, max(lens))
    canvas = _np.full((len(outs), width), padding_value, "int64")
    for i, o in enumerate(outs):
        canvas[i, : len(o)] = o
    return paddle.to_tensor(canvas), paddle.to_tensor(
        _np.asarray(lens, "int64"))


# ---- rnn api (2.x cells/layers back the 1.x names)
RNNCell = _nn.SimpleRNNCell
GRUCell = _nn.GRUCell
LSTMCell = _nn.LSTMCell


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    layer = _nn.RNN(cell, is_reverse=is_reverse, time_major=time_major)
    return layer(inputs, initial_states, sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    layer = _nn.BiRNN(cell_fw, cell_bw, time_major=time_major)
    return layer(inputs, initial_states, sequence_length)


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    hidden = size // 4
    layer = _nn.LSTM(int(input.shape[-1]), hidden,
                     direction="backward" if is_reverse else "forward")
    init = None
    if h_0 is not None:
        init = (paddle.unsqueeze(h_0, 0), paddle.unsqueeze(c_0, 0))
    out, (h, c) = layer(input, init)
    return out, c


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False):
    layer = _nn.GRU(int(input.shape[-1]), size,
                    direction="backward" if is_reverse else "forward")
    init = paddle.unsqueeze(h_0, 0) if h_0 is not None else None
    out, h = layer(input, init)
    return out


def dynamic_lstmp(input, size, proj_size, **kwargs):
    out, c = dynamic_lstm(input, size, **{k: v for k, v in kwargs.items()
                                          if k in ("h_0", "c_0", "is_reverse")})
    proj = _nn.Linear(size // 4, proj_size)
    return proj(out), c


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    layer = _nn.LSTM(int(input.shape[-1]), hidden_size, num_layers=num_layers,
                     direction="bidirect" if is_bidirec else "forward",
                     dropout=dropout_prob, time_major=True)
    out, (h, c) = layer(input, (init_h, init_c))
    return out, h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    cell = _nn.GRUCell(int(input.shape[-1]), size // 3)
    h = cell(input, hidden)
    return h[0], h[1], h[0]


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    cell = _nn.LSTMCell(int(x_t.shape[-1]), int(hidden_t_prev.shape[-1]))
    h, (hh, cc) = cell(x_t, (hidden_t_prev, cell_t_prev))
    return hh, cc
