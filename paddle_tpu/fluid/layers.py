"""fluid.layers — 1.x layer-function aliases (reference fluid/layers/*).

Ops keep their fluid argument spellings (dim/keep_dim, pool_type, act=...)
and delegate to the 2.x lowerings.
"""
from __future__ import annotations

import paddle_tpu as paddle
from .. import nn as _nn
from ..nn import functional as F
from ..static import data as _static_data
from ..static.nn import (  # noqa: F401
    batch_norm,
    conv2d,
    conv2d_transpose,
    conv3d,
    crf_decoding,
    embedding,
    fc as _fc,
    group_norm,
    instance_norm,
    layer_norm,
    nce,
    prelu,
    row_conv,
    sequence_concat,
    sequence_conv,
    sequence_enumerate,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_pad,
    sequence_pool,
    sequence_reshape,
    sequence_reverse,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
    sparse_embedding,
)

# direct re-exports where 1.x and 2.x agree
concat = paddle.concat
reshape = paddle.reshape
transpose = paddle.transpose
cast = paddle.cast
assign = paddle.assign
shape = paddle.shape
zeros = paddle.zeros
ones = paddle.ones
relu = F.relu
sigmoid = F.sigmoid
tanh = paddle.tanh
softmax = F.softmax
softmax_with_cross_entropy = F.softmax_with_cross_entropy
square = paddle.square
sqrt = paddle.sqrt
abs = paddle.abs  # noqa: A001 — fluid spelling
log = paddle.log
exp = paddle.exp
clip = paddle.clip
stack = paddle.stack
gather = paddle.gather
scatter = paddle.scatter
one_hot = F.one_hot
label_smooth = F.label_smooth
sequence_mask = F.sequence_mask


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    """fluid semantics: `input` is PROBABILITIES (softmax already applied)
    and the result is the PER-EXAMPLE loss [N, 1] — not 2.x's
    logits+mean-reduce (fluid/layers/loss.py cross_entropy)."""
    out = F.cross_entropy(input, label, soft_label=soft_label,
                          ignore_index=ignore_index, use_softmax=False,
                          reduction="none")
    return paddle.unsqueeze(out, -1) if len(out.shape) == 1 else out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    """fluid semantics: default downgrade_in_infer — kept values UNSCALED
    at train time, activations scaled by (1-p) at inference."""
    if is_test:
        if dropout_implementation == "downgrade_in_infer":
            return x * (1.0 - dropout_prob)
        return x
    return F.dropout(x, p=dropout_prob, training=True,
                     mode="upscale_in_train"
                     if dropout_implementation == "upscale_in_train"
                     else "downgrade_in_infer")


def expand(x, expand_times, name=None):
    """fluid expand == TILE by repeat counts (2.x renamed it paddle.tile;
    paddle.expand broadcasts to a target shape — different op)."""
    return paddle.tile(x, expand_times)


def split(input, num_or_sections, dim=-1, name=None):
    """fluid default splits the LAST dim and spells the axis `dim`."""
    return paddle.split(input, num_or_sections, axis=dim)


def data(name, shape, dtype="float32", append_batch_size=True, lod_level=0,
         type=None, stop_gradient=True):
    """fluid.layers.data: 1.x semantics prepend an implicit -1 batch dim
    (fluid.data / 2.x static.data do NOT — that alias lives at the fluid
    package root)."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return _static_data(name, shape, dtype)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """fluid spelling (act=/param_attr=) over static.nn.fc."""
    return _fc(input, size, num_flatten_dims=num_flatten_dims,
               weight_attr=param_attr, bias_attr=bias_attr, activation=act,
               name=name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """reference mul_op: flatten x to 2-D at x_num_col_dims and y at
    y_num_col_dims, matmul, restore x.shape[:xd] + y.shape[yd:]."""
    import numpy as np

    xs, ys = list(x.shape), list(y.shape)
    xm = paddle.reshape(x, [int(np.prod(xs[:x_num_col_dims]) or 1),
                            int(np.prod(xs[x_num_col_dims:]) or 1)])
    ym = paddle.reshape(y, [int(np.prod(ys[:y_num_col_dims]) or 1),
                            int(np.prod(ys[y_num_col_dims:]) or 1)])
    out = paddle.matmul(xm, ym)
    return paddle.reshape(out, xs[:x_num_col_dims] + ys[y_num_col_dims:])


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    out = paddle.matmul(x, y, transpose_x=transpose_x,
                        transpose_y=transpose_y)
    return out * alpha if alpha != 1.0 else out


def mean(x, name=None):
    return paddle.mean(x)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return paddle.mean(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return paddle.sum(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return paddle.max(input, axis=dim, keepdim=keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return paddle.min(input, axis=dim, keepdim=keep_dim)


def _align_y(x, y, axis):
    """fluid mid-axis broadcasting: y's dims align with x STARTING AT
    `axis` (elementwise_op semantics) — append trailing 1-dims so numpy
    broadcasting reproduces it."""
    if axis == -1 or not hasattr(y, "shape"):
        return y
    trailing = len(x.shape) - axis - len(y.shape)
    if trailing <= 0:
        return y
    return paddle.reshape(y, list(y.shape) + [1] * trailing)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.add(x, _align_y(x, y, axis)), act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.subtract(x, _align_y(x, y, axis)), act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.multiply(x, _align_y(x, y, axis)), act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _maybe_act(paddle.divide(x, _align_y(x, y, axis)), act)


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    return paddle.full(shape, value, dtype=dtype)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           name=None, data_format="NCHW"):
    if global_pooling:
        if pool_type == "max":
            return F.adaptive_max_pool2d(input, 1)
        return F.adaptive_avg_pool2d(input, 1)
    if pool_type == "max":
        return F.max_pool2d(input, pool_size, stride=pool_stride,
                            padding=pool_padding, ceil_mode=ceil_mode)
    return F.avg_pool2d(input, pool_size, stride=pool_stride,
                        padding=pool_padding, ceil_mode=ceil_mode)


def flatten(x, axis=1, name=None):
    """fluid flatten: ALWAYS 2-D — [prod(shape[:axis]), prod(shape[axis:])]
    (2.x flatten(start_axis, stop_axis) is a different op)."""
    import numpy as np

    xs = list(x.shape)
    # np.prod([]) == 1.0, and zero-size dims must stay 0 — no `or 1` fixups
    return paddle.reshape(x, [int(np.prod(xs[:axis])),
                              int(np.prod(xs[axis:]))])


def topk(input, k, name=None):
    return paddle.topk(input, k)  # last dim, values+indices (same in 1.x)


def argmax(x, axis=0, name=None):
    return paddle.argmax(x, axis=axis)  # 1.x default axis=0 (2.x flattens)


def argmin(x, axis=0, name=None):
    return paddle.argmin(x, axis=axis)


def squeeze(input, axes, name=None):
    # fluid: empty axes means squeeze EVERY size-1 dim
    return paddle.squeeze(input, axis=axes if axes else None)


def unsqueeze(input, axes, name=None):
    return paddle.unsqueeze(input, axis=axes)


def pad(x, paddings, pad_value=0.0, name=None):
    """fluid pad: flat [before0, after0, before1, after1, ...] list."""
    return paddle.nn.functional.pad(
        x, paddings, value=pad_value, mode="constant")


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    return paddle.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    if seed:  # seeded draws must be reproducible (paddle.normal has no seed)
        import jax
        import jax.numpy as jnp

        arr = mean + std * jax.random.normal(
            jax.random.key(seed), tuple(int(s) for s in shape))
        return paddle.to_tensor(arr.astype(jnp.dtype(dtype)))
    return paddle.normal(mean=mean, std=std, shape=shape).astype(dtype)


def _maybe_act(out, act):
    if act is None:
        return out
    return getattr(F, act)(out)


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)
