"""fluid.io — 1.x save/load surface (reference fluid/io.py) over the
interop-capable framework.io and the StableHLO inference exporter."""
from __future__ import annotations

from ..framework.io import (  # noqa: F401
    load,
    load_binary_tensor,
    load_binary_vars,
    save,
    save_binary_tensor,
)
from ..io import DataLoader  # noqa: F401
from ..static.io import (  # noqa: F401
    load_inference_model,
    save_inference_model,
)


def save_params(executor, dirname, main_program=None, filename=None):
    """One combined binary file of every parameter (reference
    fluid.io.save_params with `filename` -> the __params__ layout)."""
    import os

    from ..static.program import default_main_program

    prog = main_program or default_main_program()
    params = [p for p in prog.captured_params()]
    os.makedirs(dirname, exist_ok=True)
    if filename:
        with open(os.path.join(dirname, filename), "wb") as f:
            for p in params:
                save_binary_tensor(f, p)
    else:
        for p in params:
            save_binary_tensor(os.path.join(dirname, p.name or "param"), p)
    return [p.name for p in params]


def load_params(executor, dirname, main_program=None, filename=None):
    import os

    from ..static.program import default_main_program

    prog = main_program or default_main_program()
    params = [p for p in prog.captured_params()]
    if filename:
        names = [p.name for p in params]
        vals = load_binary_vars(os.path.join(dirname, filename), names)
        for p in params:
            p.set_value(vals[p.name])
    else:
        for p in params:
            p.set_value(load_binary_tensor(
                os.path.join(dirname, p.name or "param")))
