"""fluid.regularizer — 1.x spellings (reference fluid/regularizer.py)."""
from __future__ import annotations

from ..regularizer import L1Decay, L2Decay  # noqa: F401

L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
