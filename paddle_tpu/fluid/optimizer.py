"""fluid.optimizer — 1.x optimizer classes with their EXACT positional
signatures (reference fluid/optimizer.py). 1.x code passes hyperparameters
positionally (MomentumOptimizer(0.1, 0.9)), so each wrapper spells out its
own parameter order; `regularization=` maps to weight_decay and
`parameter_list=` to parameters."""
from __future__ import annotations

from ..optimizer.optimizers import (  # noqa: F401
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Momentum,
    RMSProp,
)
from ..static.extras import ExponentialMovingAverage  # noqa: F401


def _wd(regularization):
    if regularization is None:
        return None
    return getattr(regularization, "coeff", regularization)


class SGDOptimizer(SGD):
    def __init__(self, learning_rate=0.001, parameter_list=None,
                 regularization=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters=parameter_list,
                         weight_decay=_wd(regularization),
                         grad_clip=grad_clip, name=name)


class MomentumOptimizer(Momentum):
    def __init__(self, learning_rate, momentum, parameter_list=None,
                 use_nesterov=False, regularization=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, momentum=momentum,
                         parameters=parameter_list,
                         use_nesterov=use_nesterov,
                         weight_decay=_wd(regularization),
                         grad_clip=grad_clip, name=name)


class AdamOptimizer(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameter_list=None, regularization=None,
                 grad_clip=None, name=None, lazy_mode=False):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, parameters=parameter_list,
                         weight_decay=_wd(regularization),
                         grad_clip=grad_clip, lazy_mode=lazy_mode, name=name)


class AdamaxOptimizer(Adamax):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameter_list=None, regularization=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, parameters=parameter_list,
                         weight_decay=_wd(regularization),
                         grad_clip=grad_clip, name=name)


class AdagradOptimizer(Adagrad):
    def __init__(self, learning_rate, epsilon=1e-6, parameter_list=None,
                 regularization=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, epsilon=epsilon,
                         parameters=parameter_list,
                         weight_decay=_wd(regularization),
                         grad_clip=grad_clip,
                         initial_accumulator_value=initial_accumulator_value,
                         name=name)


class AdadeltaOptimizer(Adadelta):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 parameter_list=None, regularization=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, epsilon=epsilon, rho=rho,
                         parameters=parameter_list,
                         weight_decay=_wd(regularization),
                         grad_clip=grad_clip, name=name)


class RMSPropOptimizer(RMSProp):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameter_list=None, regularization=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, rho=rho, epsilon=epsilon,
                         momentum=momentum, centered=centered,
                         parameters=parameter_list,
                         weight_decay=_wd(regularization),
                         grad_clip=grad_clip, name=name)


class LambOptimizer(Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameter_list=None,
                 regularization=None, grad_clip=None, name=None):
        super().__init__(learning_rate,
                         lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                         beta2=beta2, epsilon=epsilon,
                         parameters=parameter_list, grad_clip=grad_clip,
                         name=name)
        # reference Lamb applies `regularization` as L2-into-grad SEPARATELY
        # from the decoupled lamb_weight_decay term
        if regularization is not None:
            self._weight_decay = _wd(regularization)


from ..optimizer import DecayedAdagrad, Dpsgd, Ftrl, LarsMomentum  # noqa: E402,F401
from ..incubate import LookAhead as _LookAhead, ModelAverage  # noqa: E402,F401


class DecayedAdagradOptimizer(DecayedAdagrad):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 parameter_list=None, regularization=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, decay=decay, epsilon=epsilon,
                         parameters=parameter_list, grad_clip=grad_clip)


class FtrlOptimizer(Ftrl):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameter_list=None, regularization=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, l1=l1, l2=l2, lr_power=lr_power,
                         parameters=parameter_list, grad_clip=grad_clip)


class DpsgdOptimizer(Dpsgd):
    def __init__(self, learning_rate=0.001, clip=0.9, batch_size=0.999,
                 sigma=1e-8, parameter_list=None, seed=0, name=None):
        super().__init__(learning_rate, clip=clip, batch_size=batch_size,
                         sigma=sigma, parameters=parameter_list, seed=seed)


class LarsMomentumOptimizer(LarsMomentum):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameter_list=None,
                 regularization=None, grad_clip=None, name=None,
                 exclude_from_weight_decay=None, epsilon=0):
        super().__init__(learning_rate, momentum=momentum,
                         lars_coeff=lars_coeff,
                         lars_weight_decay=lars_weight_decay,
                         parameters=parameter_list, grad_clip=grad_clip)


class LookaheadOptimizer:
    """reference: fluid/optimizer.py LookaheadOptimizer(inner, alpha, k) —
    argument order differs from incubate.LookAhead."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self._impl = _LookAhead(inner_optimizer, alpha=alpha, k=k)

    def __getattr__(self, name):
        return getattr(self.__dict__["_impl"], name)


class RecomputeOptimizer:
    """reference: fluid/optimizer.py RecomputeOptimizer — checkpointed
    backward. Recompute lives in fleet.recompute on this runtime; the
    wrapper keeps 1.x call sites compiling and applies activation
    checkpointing through the model's recompute flags."""

    def __init__(self, optimizer):
        self._inner = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)


class PipelineOptimizer:
    """reference: fluid/optimizer.py PipelineOptimizer — static pipeline
    via device_guard program splitting (static/pipeline.py)."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self._inner = optimizer
        self.num_microbatches = num_microbatches

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program=startup_program,
                                    parameter_list=parameter_list)

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)
