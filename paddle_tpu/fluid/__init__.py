"""paddle.fluid compat namespace — the 1.x/fluid-style API real Paddle 2.3
still ships and much ecosystem code still imports.

Reference analog: python/paddle/fluid/__init__.py. Everything here is a
THIN alias onto the first-class modules (static/, nn/, optimizer/, core/):
no behavior lives in this package, so fluid-style scripts run against the
same TPU execution paths as 2.x-style code. Coverage targets the surface
migration guides lean on (fluid.data, fluid.layers.fc/embedding/...,
fluid.optimizer.*Optimizer, fluid.dygraph, initializer/regularizer/io);
exotic fluid corners raise AttributeError rather than pretending.
"""
from __future__ import annotations

from .. import nn as _nn
from ..core.place import CPUPlace, CUDAPinnedPlace, CUDAPlace  # noqa: F401
from ..core.ragged import LoDTensor, create_lod_tensor  # noqa: F401
from ..framework.io import load as _load, save as _save  # noqa: F401
from ..static import (  # noqa: F401
    CompiledProgram,
    Executor,
    Program,
    Variable,
    data,
    default_main_program,
    default_startup_program,
    global_scope,
    program_guard,
    scope_guard,
)

ParamAttr = _nn.ParamAttr

from . import dygraph  # noqa: E402,F401
from . import initializer  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import layers  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401


def is_compiled_with_cuda():
    return False


def cuda_places(device_ids=None):
    return []


def cpu_places(device_count=1):
    return [CPUPlace() for _ in range(device_count)]
