"""paddle.fluid compat namespace — the 1.x/fluid-style API real Paddle 2.3
still ships and much ecosystem code still imports.

Reference analog: python/paddle/fluid/__init__.py. Everything here is a
THIN alias onto the first-class modules (static/, nn/, optimizer/, core/):
no behavior lives in this package, so fluid-style scripts run against the
same TPU execution paths as 2.x-style code. Coverage targets the surface
migration guides lean on (fluid.data, fluid.layers.fc/embedding/...,
fluid.optimizer.*Optimizer, fluid.dygraph, initializer/regularizer/io);
exotic fluid corners raise AttributeError rather than pretending.
"""
from __future__ import annotations

from .. import nn as _nn
from ..core.place import CPUPlace, CUDAPinnedPlace, CUDAPlace  # noqa: F401
from ..core.ragged import LoDTensor, create_lod_tensor  # noqa: F401
from ..framework.io import load as _load, save as _save  # noqa: F401
from ..static import (  # noqa: F401
    CompiledProgram,
    Executor,
    Program,
    Variable,
    data,
    default_main_program,
    default_startup_program,
    global_scope,
    program_guard,
    scope_guard,
)

ParamAttr = _nn.ParamAttr

from . import dygraph  # noqa: E402,F401
from . import initializer  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import layers  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401


def is_compiled_with_cuda():
    return False


def cuda_places(device_ids=None):
    return []


def cpu_places(device_count=1):
    return [CPUPlace() for _ in range(device_count)]


# ---------------------------------------------------------------- batch 2
# (reference fluid/__init__.py exports the long 1.x surface: submodule
# aliases, flag/env helpers, legacy metric classes, profiler shims)
from ..static import (  # noqa: E402,F401
    BuildStrategy,
    ExecutionStrategy,
    WeightNormParamAttr,
    device_guard,
    gradients,
)
from ..static import nn as _static_nn  # noqa: E402
from ..static.extras import load as load, save as save  # noqa: E402,F401
from ..utils import unique_name  # noqa: E402,F401
from ..utils.flags import get_flags, set_flags  # noqa: E402,F401

embedding = layers.embedding
one_hot = layers.one_hot


class _BackwardModule:
    """fluid.backward.append_backward / gradients (reference
    fluid/backward.py)."""

    @staticmethod
    def append_backward(loss, parameter_list=None, no_grad_set=None,
                        callbacks=None, checkpoints=None):
        from ..static import append_backward as _impl

        return _impl(loss, parameter_list=parameter_list)

    @staticmethod
    def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
        from ..static import gradients as _impl

        return _impl(targets, inputs, target_gradients)


backward = _BackwardModule()


class _ClipModule:
    """fluid.clip — 1.x gradient-clip class names (reference fluid/clip.py)."""

    def __getattr__(self, name):
        from .. import nn as _nn2

        mapping = {
            "GradientClipByGlobalNorm": _nn2.ClipGradByGlobalNorm,
            "GradientClipByNorm": _nn2.ClipGradByNorm,
            "GradientClipByValue": _nn2.ClipGradByValue,
            "set_gradient_clip": lambda clip, param_list=None, program=None:
                None,  # 2.x: pass grad_clip to the optimizer instead
        }
        if name in mapping:
            return mapping[name]
        raise AttributeError(name)


clip = _ClipModule()


import contextlib as _contextlib


@_contextlib.contextmanager
def name_scope(prefix=None):
    """reference: fluid/framework.py name_scope — a naming context for
    debug/visualization; unique_name guard scopes generated names."""
    with unique_name.guard((prefix or "") + "/" if prefix else None):
        yield


def in_dygraph_mode():
    import paddle_tpu as _p

    return _p.in_dynamic_mode()


_dygraph_enable = dygraph.enable_dygraph
_dygraph_disable = dygraph.disable_dygraph
enable_dygraph = _dygraph_enable
disable_dygraph = _dygraph_disable


def load_op_library(lib_filename):
    """reference: fluid/framework.py load_op_library — out-of-tree op .so.
    Custom ops register through utils.custom_op in this build."""
    raise NotImplementedError(
        "load_op_library loads CUDA op libraries; register TPU custom ops "
        "with paddle.utils.custom_op.register_op (utils/custom_op.py)")


def require_version(min_version, max_version=None):
    from ..utils import require_version as _impl

    return _impl(min_version, max_version)


def memory_optimize(input_program=None, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """reference: deprecated no-op since 1.6 (buffer reuse is the runtime's
    job — here XLA's)."""


def release_memory(input_program, skip_opt_set=None):
    """reference: deprecated no-op (see memory_optimize)."""


class _InstallCheck:
    """fluid.install_check module shape: fluid.install_check.run_check()."""

    @staticmethod
    def run_check():
        import paddle_tpu as _p

        return _p.utils.run_check()


install_check = _InstallCheck()


class DataFeeder:
    """reference: fluid/data_feeder.py DataFeeder — turn reader rows into
    executor feed dicts."""

    def __init__(self, feed_list, place=None, program=None):
        self.names = [getattr(v, "name", str(v)) for v in feed_list]

    def feed(self, iterable):
        import numpy as _np

        rows = list(iterable)
        cols = list(zip(*rows))
        return {n: _np.asarray(c) for n, c in zip(self.names, cols)}


class _Metrics:
    """fluid.metrics legacy classes (reference fluid/metrics.py):
    update()-protocol wrappers over paddle.metric."""

    class Accuracy:
        def __init__(self, name=None):
            self._correct = 0.0
            self._total = 0.0

        def update(self, value, weight):
            self._correct += float(value) * float(weight)
            self._total += float(weight)

        def eval(self):
            return self._correct / max(self._total, 1e-12)

        def reset(self):
            self._correct = self._total = 0.0

    class Auc:
        def __init__(self, name=None, curve="ROC", num_thresholds=4095):
            from ..metric import Auc as _Auc2

            self._m = _Auc2(curve=curve, num_thresholds=num_thresholds)

        def update(self, preds, labels):
            self._m.update(preds, labels)

        def eval(self):
            return self._m.accumulate()

        def reset(self):
            self._m.reset()

    class Precision:
        def __init__(self, name=None):
            self.tp = 0
            self.fp = 0

        def update(self, preds, labels):
            import numpy as _np

            p = (_np.asarray(preds).reshape(-1) > 0.5).astype(int)
            l = _np.asarray(labels).reshape(-1)
            self.tp += int(((p == 1) & (l == 1)).sum())
            self.fp += int(((p == 1) & (l == 0)).sum())

        def eval(self):
            return self.tp / max(self.tp + self.fp, 1e-12)

        def reset(self):
            self.tp = self.fp = 0

    class Recall:
        def __init__(self, name=None):
            self.tp = 0
            self.fn = 0

        def update(self, preds, labels):
            import numpy as _np

            p = (_np.asarray(preds).reshape(-1) > 0.5).astype(int)
            l = _np.asarray(labels).reshape(-1)
            self.tp += int(((p == 1) & (l == 1)).sum())
            self.fn += int(((p == 0) & (l == 1)).sum())

        def eval(self):
            return self.tp / max(self.tp + self.fn, 1e-12)

        def reset(self):
            self.tp = self.fn = 0


metrics = _Metrics()


class _FluidProfiler:
    """fluid.profiler legacy API (reference fluid/profiler.py) over the
    host tracer."""

    @staticmethod
    def start_profiler(state="All", tracer_option="Default"):
        from ..profiler import host_tracer

        host_tracer().clear()

    @staticmethod
    def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
        from ..profiler import summary

        summary()

    @staticmethod
    @__import__("contextlib").contextmanager
    def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
                 tracer_option="Default"):
        _FluidProfiler.start_profiler(state)
        try:
            yield
        finally:  # an exception in the profiled block must not lose the data
            _FluidProfiler.stop_profiler(sorted_key, profile_path)


profiler = _FluidProfiler()


class _Contrib:
    """fluid.contrib subset: the pieces migration guides reference."""

    class mixed_precision:  # noqa: N801 — module-style alias
        @staticmethod
        def decorate(optimizer, init_loss_scaling=2 ** 15,
                     use_dynamic_loss_scaling=True, **kw):
            from ..amp import decorate as _impl

            return _impl(optimizer=optimizer,
                         init_loss_scaling=init_loss_scaling)

    class sparsity:  # noqa: N801
        @staticmethod
        def decorate(optimizer):
            from ..incubate import asp

            return asp.decorate(optimizer)

        @staticmethod
        def prune_model(model, **kw):
            from ..incubate import asp

            return asp.prune_model(model, **kw)


contrib = _Contrib()

# submodule-style aliases 1.x scripts import through fluid
from ..static import executor as _noop_exec  # noqa: E402,F401 — if absent, skip


class _CoreShim:
    """fluid.core — the reference's pybind module. Legacy code imports a
    handful of types/utilities from it; expose the runtime equivalents."""

    from ..core.place import CPUPlace, CUDAPinnedPlace, CUDAPlace  # noqa: F401
    from ..core.ragged import LoDTensor  # noqa: F401
    from ..core.selected_rows import SelectedRows  # noqa: F401

    class VarDesc:
        class VarType:
            FP32 = 5
            FP64 = 6
            FP16 = 4
            BF16 = 22
            INT32 = 2
            INT64 = 3
            BOOL = 0
            UINT8 = 20
            INT8 = 21
            LOD_TENSOR = 7
            SELECTED_ROWS = 8

    @staticmethod
    def is_compiled_with_cuda():
        return False

    @staticmethod
    def is_compiled_with_xpu():
        return False

    @staticmethod
    def is_compiled_with_npu():
        return False

    @staticmethod
    def get_cuda_device_count():
        return 0

    @staticmethod
    def globals():
        from ..utils.flags import _FLAGS

        return dict(_FLAGS)


core = _CoreShim()
_Contrib.slim = __import__("paddle_tpu.quantization",
                           fromlist=["quantization"])
