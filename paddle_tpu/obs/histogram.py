"""Fixed-bucket streaming histograms for serving latency metrics.

Design constraints, in order:

- **Bounded memory.** A serving engine observes one latency sample per
  request (TTFT, TPOT, queue wait, e2e) and two per step (duration,
  occupancy) forever; storing raw samples grows without bound. A fixed
  bucket layout costs ``len(edges) + 1`` ints for the life of the process
  — the same shape Prometheus client histograms use, so the exporter in
  obs/export.py renders the classic ``_bucket{le=...}`` series directly.
- **O(log buckets) observe.** ``observe`` is a bisect + two adds — cheap
  enough to sit on the engine's step boundary without showing up in the
  obs-on-vs-off bench delta.
- **Pre-seeded presence** (the PT003/PT008 contract): a histogram exists —
  and its percentile gauges read 0 — from construction, not from its first
  sample, so dashboards keyed on metric presence never miss the early
  window of an incident.

Percentiles are estimated by linear interpolation inside the bucket that
holds the requested rank (the standard Prometheus ``histogram_quantile``
estimator): exact at bucket edges, within one bucket width everywhere
else. The overflow bucket is reported as its lower edge — a deliberate
underestimate that keeps a single runaway sample from painting p99 as
infinity.
"""
from __future__ import annotations

from bisect import bisect_left

__all__ = ["Histogram", "HistogramFamily", "LATENCY_EDGES_S",
           "OCCUPANCY_EDGES", "QUANTILES", "percentile_from_counts",
           "split_labels"]

# Latency edges in seconds: ~Prometheus default widened to cover both a
# microbenchmark CPU step (sub-millisecond) and a multi-minute queue wait.
LATENCY_EDGES_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

# Batch-occupancy edges: small integers exact, powers of two beyond — a
# decode batch is a slot count, not a duration.
OCCUPANCY_EDGES = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0,
                   32.0, 64.0, 128.0, 256.0)

# The quantiles every serving histogram publishes: (suffix, q).
QUANTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))


def percentile_from_counts(edges, counts, q: float,
                           count: int | None = None) -> float:
    """The histogram_quantile estimator over a raw bucket-count vector
    (``len(edges) + 1`` entries, last = overflow). Shared by
    :meth:`Histogram.percentile` and callers holding count DELTAS — the
    SLO admission controller computes windowed p99s by subtracting two
    snapshots of a cumulative histogram's counts and estimating over the
    difference, without a second histogram on the hot path."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile {q} outside [0, 1]")
    count = sum(counts) if count is None else count
    if count == 0:
        return 0.0
    target = q * count
    cum = 0
    for i, c in enumerate(counts):
        if cum + c >= target:
            if i == len(edges):  # overflow: clamp, don't invent
                return edges[-1]
            lo = 0.0 if i == 0 else edges[i - 1]
            hi = edges[i]
            frac = (target - cum) / c if c else 0.0
            return lo + frac * (hi - lo)
        cum += c
    return edges[-1]


class Histogram:
    """Fixed-bucket histogram: bucket ``i`` counts samples in
    ``(edges[i-1], edges[i]]`` (bucket 0 is ``(-inf, edges[0]]``), plus one
    overflow bucket above ``edges[-1]``. Tracks ``count``/``sum`` so mean
    and Prometheus exposition come for free."""

    def __init__(self, name: str, edges=LATENCY_EDGES_S):
        edges = tuple(float(e) for e in edges)
        if len(edges) < 2 or any(a >= b for a, b in zip(edges, edges[1:])):
            raise ValueError(
                f"histogram {name!r}: edges must be >= 2 strictly "
                f"increasing values, got {edges}")
        self.name = name
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)  # + overflow bucket
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        """O(log buckets): bisect to the owning bucket, bump two counters."""
        v = float(value)
        self.counts[bisect_left(self.edges, v)] += 1
        self.count += 1
        self.sum += v

    def reset(self) -> None:
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Linear interpolation inside the bucket holding rank ``q *
        count`` (the histogram_quantile estimator). 0.0 for an empty
        histogram; the first bucket interpolates from 0 (these are
        non-negative measurements); the overflow bucket clamps to the top
        edge rather than extrapolating to infinity."""
        return percentile_from_counts(self.edges, self.counts, q,
                                      self.count)

    def snapshot(self) -> dict:
        """Percentiles + count/sum/mean, always present (zeros when
        empty), keyed by the quantile suffixes the metrics registry
        publishes."""
        out = {suffix: self.percentile(q) for suffix, q in QUANTILES}
        out.update(count=self.count, sum=self.sum, mean=self.mean)
        return out

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """``(upper_edge, cumulative_count)`` pairs, Prometheus
        ``_bucket{le=...}`` shaped; the final pair is ``(inf, count)``."""
        out, cum = [], 0
        for edge, c in zip(self.edges, self.counts):
            cum += c
            out.append((edge, cum))
        out.append((float("inf"), self.count))
        return out

    def __repr__(self) -> str:
        return (f"Histogram({self.name!r}, count={self.count}, "
                f"p50={self.percentile(0.5):.4g}, "
                f"p99={self.percentile(0.99):.4g})")


def split_labels(name: str) -> tuple[str, dict]:
    """Parse a ``base{k=v,k2=v2}`` metric name into (base, labels) —
    the registry-key convention labeled families use. A plain name
    returns ``(name, {})``."""
    if "{" not in name or not name.endswith("}"):
        return name, {}
    base, _, body = name.partition("{")
    labels: dict[str, str] = {}
    for part in body[:-1].split(","):
        k, _, v = part.partition("=")
        labels[k] = v
    return base, labels


class HistogramFamily:
    """A label-keyed family of fixed-bucket histograms sharing one base
    name — the mechanism behind ``serving_step_phase_s{phase=}`` (and the
    per-tenant TTFT/TPOT classes the fleet router will reuse: the label
    key is arbitrary). Children are created on first observation; the
    declared ``values`` exist — and publish zeros — from construction,
    the same presence contract the scalar ``_SEEDED`` registry enforces.
    Each child is a plain :class:`Histogram` named
    ``base{label=value}``, so every exporter that understands labeled
    names renders it with no extra plumbing."""

    def __init__(self, name: str, label: str, edges=LATENCY_EDGES_S,
                 values=()):
        self.name = name
        self.label = label
        self.edges = tuple(edges)
        self._children: dict[str, Histogram] = {}
        for v in values:
            self.child(v)

    def child(self, value) -> Histogram:
        """The child histogram for one label value (created pre-seeded
        when absent)."""
        key = str(value)
        h = self._children.get(key)
        if h is None:
            h = Histogram(f"{self.name}{{{self.label}={key}}}", self.edges)
            self._children[key] = h
        return h

    def observe(self, value, sample: float) -> None:
        self.child(value).observe(sample)

    def children(self) -> dict[str, Histogram]:
        """{label value: child histogram}, insertion-ordered."""
        return dict(self._children)

    def reset(self) -> None:
        for h in self._children.values():
            h.reset()

    def __len__(self) -> int:
        return len(self._children)
