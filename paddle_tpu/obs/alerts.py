"""Anomaly watchdogs: the pathologies the suite already knows about,
detected live as structured alerts.

Every rule is evaluated once per engine step, at the step boundary, off
values that are ALREADY host-resident — the just-built
:class:`~paddle_tpu.obs.timeline.StepRecord` plus a small dict of
monotonic counter totals the engine reads out of its own host state and
the monitor registry. Zero device syncs are added (the SyncTally
decode-loop certification is pinned with watchdogs on), and every rule
is EDGE-TRIGGERED: it fires once when its condition onsets and stays
quiet while the condition merely persists, so a deterministic scenario
fires each rule exactly once and a clean run fires none.

The rules, each a regression this repo has already shipped machinery
against:

- ``retrace_after_warmup`` — a CompileGuard counted a trace beyond its
  declared budget after the warmup window: the compile-once contract
  broke in production, exactly what the retrace explainer exists for.
- ``pallas_fallback`` — ``serving_pallas_fallback_total`` grew: a hot
  dispatch silently degraded to the composite path (the certified
  steady state is 0; this is the silent-MFU-loss PR 11 surfaced).
- ``spec_acceptance_collapse`` — the windowed speculative acceptance
  rate fell below the floor with enough proposals to mean it: the draft
  stopped tracking the target and every verify step is mostly wasted
  FLOPs.
- ``eviction_thrash`` — prefix-page evictions + host-tier spills in the
  window crossed the threshold: the pool is churning its warm prefixes
  instead of serving from them.
- ``queue_stall`` — requests are waiting but nothing was admitted and
  nothing is running for N consecutive steps: the engine is wedged (or
  paused with work queued), not merely busy.
- ``slo_burn`` — a tenant's windowed SLO-violation fraction (violation
  retirements / total retirements, from the per-tenant goodput ledger —
  obs/tenant.py) crossed the threshold with enough retirements to mean
  it: that tenant's latency promise is burning, per-tenant and latched
  (re-arms only after a healthy window), the request-grain twin of the
  engine-grain rules above.

Each firing appends an :class:`Alert` to a bounded history ring, bumps
the pre-seeded ``serving_alerts_total{rule=}`` counter family (via the
engine), and renders as an instant on the Chrome-trace engine track —
and the whole history rides along in every flight-record dump.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

__all__ = ["Alert", "WatchdogConfig", "Watchdog", "RULES"]

#: every rule name — the pre-seeded label set of serving_alerts_total{rule=}
RULES = ("retrace_after_warmup", "pallas_fallback",
         "spec_acceptance_collapse", "eviction_thrash", "queue_stall",
         "slo_burn")


@dataclass(frozen=True)
class Alert:
    """One watchdog firing — the structured record the flight recorder
    dumps and the Chrome export renders as an engine-track instant."""
    rule: str
    step: int       # engine step index the rule fired at
    t: float        # engine-clock seconds
    message: str
    data: dict = field(default_factory=dict)

    def asdict(self) -> dict:
        return {"rule": self.rule, "step": self.step, "t": self.t,
                "message": self.message, "data": dict(self.data)}


@dataclass(frozen=True)
class WatchdogConfig:
    """Thresholds; the defaults are deliberately conservative — a clean
    engine (the demo, the bench) must never fire."""
    warmup_steps: int = 8           # retrace rule arms after this step
    acceptance_floor: float = 0.1   # windowed spec acceptance below = bad
    acceptance_min_proposed: int = 64  # proposals before the rate means much
    acceptance_window_steps: int = 16  # spec acceptance window
    thrash_window_steps: int = 16
    thrash_events: int = 8          # evictions + spills in the window
    stall_steps: int = 4            # consecutive no-progress steps
    slo_burn_window_steps: int = 16  # per-tenant retirement window
    slo_burn_threshold: float = 0.5  # violation fraction that fires
    slo_burn_min_retired: int = 4   # retirements before the fraction
    # means anything (one late request out of one is not a burn)
    capacity: int = 256             # alert history ring bound

    def validate(self) -> None:
        if self.warmup_steps < 0:
            raise ValueError(f"warmup_steps {self.warmup_steps} < 0")
        if not 0.0 < self.acceptance_floor < 1.0:
            raise ValueError(
                f"acceptance_floor {self.acceptance_floor} outside (0, 1)")
        if not 0.0 < self.slo_burn_threshold <= 1.0:
            raise ValueError(
                f"slo_burn_threshold {self.slo_burn_threshold} outside "
                f"(0, 1]")
        for name in ("acceptance_min_proposed", "acceptance_window_steps",
                     "thrash_window_steps", "thrash_events", "stall_steps",
                     "slo_burn_window_steps", "slo_burn_min_retired",
                     "capacity"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} {getattr(self, name)} < 1")


class Watchdog:
    """The rule engine. ``on_step(record, counters)`` evaluates every
    rule against one step and returns the alerts that fired (possibly
    empty). ``counters`` carries monotonic TOTALS (retraces, fallbacks,
    proposed, accepted, evictions, spills) — the watchdog keeps its own
    baselines and windows, so callers just hand it the current values.
    """

    def __init__(self, config: WatchdogConfig | None = None, clock=None):
        self.cfg = config or WatchdogConfig()
        self.cfg.validate()
        self._clock = clock or (lambda: 0.0)
        self.history: deque[Alert] = deque(maxlen=self.cfg.capacity)
        self.fired_total: dict[str, int] = {rule: 0 for rule in RULES}
        # baselines / windows
        self._retraces = 0
        self._fallbacks = 0
        self._spec_win: deque[tuple[int, int]] = deque(
            maxlen=self.cfg.acceptance_window_steps)
        self._spec_last = (0, 0)
        self._spec_latched = False
        self._thrash_win: deque[int] = deque(
            maxlen=self.cfg.thrash_window_steps)
        self._thrash_last = 0
        self._stall_streak = 0
        # slo_burn: per-tenant (violation, retired) delta windows, last
        # totals, and the per-tenant latch
        self._burn_win: dict[str, deque] = {}
        self._burn_last: dict[str, tuple[int, int]] = {}
        self._burn_latched: set[str] = set()

    def _fire(self, out: list, rule: str, step: int, message: str,
              **data) -> None:
        alert = Alert(rule, step, self._clock(), message, data)
        self.history.append(alert)
        self.fired_total[rule] += 1
        out.append(alert)

    def on_step(self, record, counters: dict) -> list[Alert]:
        cfg = self.cfg
        out: list[Alert] = []
        step = record.step

        # retrace after warmup: the compile-once contract broke live
        retraces = int(counters.get("retraces", 0))
        if retraces > self._retraces and step >= cfg.warmup_steps:
            self._fire(out, "retrace_after_warmup", step,
                       f"{retraces - self._retraces} over-budget "
                       f"retrace(s) at step {step} (after the "
                       f"{cfg.warmup_steps}-step warmup)",
                       retraces_total=retraces)
        self._retraces = retraces

        # pallas fallback: a hot dispatch lost its fast kernel
        fallbacks = int(counters.get("fallbacks", 0))
        if fallbacks > self._fallbacks:
            self._fire(out, "pallas_fallback", step,
                       f"{fallbacks - self._fallbacks} Pallas dispatch(es) "
                       f"degraded to the composite path",
                       fallbacks_total=fallbacks)
        self._fallbacks = fallbacks

        # speculative acceptance collapse, windowed and latched: fire at
        # the collapse edge, re-arm only after a healthy window
        proposed = int(counters.get("proposed", 0))
        accepted = int(counters.get("accepted", 0))
        lp, la = self._spec_last
        self._spec_last = (proposed, accepted)
        self._spec_win.append((proposed - lp, accepted - la))
        wp = sum(d[0] for d in self._spec_win)
        wa = sum(d[1] for d in self._spec_win)
        if wp >= cfg.acceptance_min_proposed:
            rate = wa / wp
            if rate < cfg.acceptance_floor and not self._spec_latched:
                self._spec_latched = True
                self._fire(out, "spec_acceptance_collapse", step,
                           f"windowed speculative acceptance {rate:.3f} "
                           f"below floor {cfg.acceptance_floor} "
                           f"({wa}/{wp} over {len(self._spec_win)} steps)",
                           window_proposed=wp, window_accepted=wa,
                           rate=rate)
            elif rate >= cfg.acceptance_floor:
                self._spec_latched = False

        # eviction/spill thrash: warm prefixes churning out of the pool
        ev = int(counters.get("evictions", 0)) + int(
            counters.get("spills", 0))
        self._thrash_win.append(ev - self._thrash_last)
        self._thrash_last = ev
        wev = sum(self._thrash_win)
        if wev >= cfg.thrash_events:
            self._fire(out, "eviction_thrash", step,
                       f"{wev} prefix evictions + host-tier spills in "
                       f"{len(self._thrash_win)} steps (threshold "
                       f"{cfg.thrash_events})",
                       window_events=wev)
            self._thrash_win.clear()  # re-arm after another full thrash

        # slo burn, per-tenant, windowed and latched like the acceptance
        # rule: the ledger hands monotonic (violations, retired) totals;
        # fire at the onset edge, re-arm only after a healthy window
        for tenant, (v, r) in (counters.get("tenant_slo") or {}).items():
            win = self._burn_win.get(tenant)
            if win is None:
                win = self._burn_win[tenant] = deque(
                    maxlen=cfg.slo_burn_window_steps)
            lv, lr = self._burn_last.get(tenant, (0, 0))
            self._burn_last[tenant] = (v, r)
            win.append((v - lv, r - lr))
            wv = sum(d[0] for d in win)
            wr = sum(d[1] for d in win)
            if wr < cfg.slo_burn_min_retired:
                # too few retirements to judge a burn — but a FULL window
                # with zero violations is unambiguously healthy, and must
                # re-arm the latch even for a low-rate tenant (otherwise a
                # sparse tenant's first burn latches forever and every
                # later episode is silently missed)
                if wv == 0 and len(win) == win.maxlen:
                    self._burn_latched.discard(tenant)
                continue
            frac = wv / wr
            if frac >= cfg.slo_burn_threshold:
                if tenant not in self._burn_latched:
                    self._burn_latched.add(tenant)
                    self._fire(out, "slo_burn", step,
                               f"tenant {tenant!r} windowed SLO-violation "
                               f"fraction {frac:.3f} at/above threshold "
                               f"{cfg.slo_burn_threshold} ({wv}/{wr} "
                               f"retirements over {len(win)} steps)",
                               tenant=tenant, window_violations=wv,
                               window_retired=wr, fraction=frac)
            else:
                self._burn_latched.discard(tenant)

        # queue stall: waiting work, zero progress, N consecutive steps
        stalled = (record.queue_depth > 0 and record.admitted == 0
                   and record.batch == 0 and record.chunks == 0)
        self._stall_streak = self._stall_streak + 1 if stalled else 0
        if self._stall_streak == cfg.stall_steps:
            self._fire(out, "queue_stall", step,
                       f"{record.queue_depth} request(s) waiting with no "
                       f"admission and nothing running for "
                       f"{cfg.stall_steps} consecutive steps",
                       queue_depth=record.queue_depth)

        return out

    def alerts(self) -> list[Alert]:
        """The retained alert history, oldest first."""
        return list(self.history)
