"""Request-journey records: the wire-exportable trace of one request's
trip through the engine.

A :class:`Journey` is the request-grain complement of the engine-grain
flight recorder: every request accrues an ordered list of **hops** —
enqueue → router hops when a fleet router is in front (``routed`` /
``spilled`` with the chosen replica and warm-prefix width, or ``shed``
when the router retires it unserved) → admit (queue delay, prefix-hit
width, restore/spill page refs) → each prefill chunk → decode/verify
step refs (with accepted counts under speculation) →
preemptions/swaps → retire (terminal state) — each
hop stamped with the ENGINE STEP INDEX it happened in and the engine
clock time. Nothing here reads the device: journeys are assembled
purely from the lifecycle events the tracer and scheduler already stamp
(the :class:`~paddle_tpu.obs.trace.Tracer` ``journal`` hook replays
every event into the book) plus the engine's host-resident step
counter, so the SyncTally decode-loop certification is byte-identical
with journeys on.

The wire format (:meth:`Journey.to_wire`, schema
``paddle-tpu/journey/v1``, gated by :func:`validate_journey`) is a
plain JSON dict — THE trace-export-over-the-wire format the multi-host
arc consumes: a prefill host can ship a request's journey-so-far to the
decode host and the fleet router can aggregate retired journeys across
replicas without any shared memory. The flight recorder embeds a
bounded ring of these dicts (schema v2), and
``python -m paddle_tpu.obs --journey RID`` pretty-prints one out of a
dump.

Bounds: the book retains ``capacity`` journeys (oldest TERMINAL evicted
first — live journeys are never truncated mid-lifecycle, the Tracer
retention contract) and each journey caps its hop list at ``max_hops``
(``dropped_hops`` counts the overflow; the terminal retire hop is
always recorded). Imports nothing from ``paddle_tpu.serving`` —
serving imports us.
"""
from __future__ import annotations

from collections import OrderedDict

__all__ = ["JOURNEY_SCHEMA", "JOURNEY_KINDS", "Journey", "JourneyBook",
           "validate_journey", "format_journey"]

JOURNEY_SCHEMA = "paddle-tpu/journey/v1"

#: trace event name -> journey hop kind (events not listed here — e.g.
#: the cadenced decode marks' enclosing spans — don't become hops)
_EVENT_KINDS = {
    "enqueued": "enqueue",
    "admitted": "admit",
    "spill": "spill",
    "restore": "restore",
    "prefill_start": "prefill_start",
    "prefill_chunk": "prefill_chunk",
    "prefill_end": "prefill_end",
    "first_token": "first_token",
    "decode_mark": "decode",
    "spec_verify": "verify",
    "preempted": "preempt",
    "swap_out": "swap_out",
    "swap_in": "swap_in",
    "resumed": "resume",
    "pallas_fallback": "fallback",
    "retired": "retire",
    # fleet-router hops (PR 16): the router stamps these on the owning
    # replica's tracer before the engine's own lifecycle events, a
    # version-compatible v1 extension (JOURNEY_KINDS grows, nothing moves)
    "routed": "routed",
    "spilled": "spilled",
    "shed_by_router": "shed",
    # wire-transport hops (PR 17): stamped by the router around its
    # transport exchanges — the same v1-compatible extension shape
    # (JOURNEY_KINDS grows, nothing moves, old dumps stay valid)
    "wire_retry": "wire_retry",
    "refetch_fallback": "refetch_fallback",
    "breaker_open": "breaker_open",
}

#: every hop kind a validate_journey-clean record may carry
JOURNEY_KINDS = frozenset(_EVENT_KINDS.values())

# wire-dict required keys and types (latency fields are float-or-None,
# checked separately; "state" is str-or-None — None = still in flight)
_WIRE_KEYS = (("schema", str), ("rid", int), ("tenant", str),
              ("tokens", int), ("preemptions", int),
              ("prefix_hit_tokens", int), ("dropped_hops", int),
              ("hops", list))
_WIRE_LATENCIES = ("queue_delay_s", "ttft_s", "tpot_s", "e2e_s")


class Journey:
    """One request's hop list + derived latency fields. Mutated only by
    the owning :class:`JourneyBook`; read anywhere."""

    __slots__ = ("rid", "tenant", "state", "hops", "dropped_hops",
                 "max_hops", "tokens", "preemptions", "prefix_hit_tokens",
                 "enqueued_t", "admitted_t", "first_token_t", "retired_t")

    def __init__(self, rid: int, tenant: str, max_hops: int):
        self.rid = rid
        self.tenant = tenant
        self.state: str | None = None  # terminal state once retired
        self.hops: list[dict] = []
        self.dropped_hops = 0
        self.max_hops = max_hops
        self.tokens = 0
        self.preemptions = 0
        self.prefix_hit_tokens = 0
        self.enqueued_t: float | None = None
        self.admitted_t: float | None = None
        self.first_token_t: float | None = None
        self.retired_t: float | None = None

    @property
    def terminal(self) -> bool:
        return self.state is not None

    def _hop(self, kind: str, step: int, t: float, data: dict) -> None:
        if kind != "retire" and len(self.hops) >= self.max_hops:
            # bounded: long decodes overflow into the drop counter; the
            # terminal hop is always kept (a journey must end)
            self.dropped_hops += 1
            return
        hop = {"kind": kind, "step": int(step), "t": float(t)}
        hop.update(data)
        self.hops.append(hop)

    # ------------------------------------------------------- derived views
    def _dt(self, t: float | None) -> float | None:
        if t is None or self.enqueued_t is None:
            return None
        return t - self.enqueued_t

    def to_wire(self) -> dict:
        """The schema-versioned JSON-ready dict — the over-the-wire
        journey format. Latency fields are None for milestones this
        lifecycle never reached (a shed request has no TTFT)."""
        tpot = None
        if self.state == "finished" and self.tokens > 1 \
                and self.first_token_t is not None \
                and self.retired_t is not None:
            # finished requests retire at the step boundary that emitted
            # their last token, so retirement time IS last-token time
            # (the RequestTrace.summary tpot contract)
            tpot = (self.retired_t - self.first_token_t) / (self.tokens - 1)
        return {
            "schema": JOURNEY_SCHEMA,
            "rid": self.rid,
            "tenant": self.tenant,
            "state": self.state,
            "tokens": self.tokens,
            "preemptions": self.preemptions,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "queue_delay_s": self._dt(self.admitted_t),
            "ttft_s": self._dt(self.first_token_t),
            "tpot_s": tpot,
            "e2e_s": self._dt(self.retired_t),
            "dropped_hops": self.dropped_hops,
            "hops": [dict(h) for h in self.hops],
        }

    def __repr__(self) -> str:
        return (f"Journey(rid={self.rid}, tenant={self.tenant!r}, "
                f"state={self.state}, hops={len(self.hops)})")


class JourneyBook:
    """Engine-owned journey store, fed by the tracer's ``journal`` hook.
    ``step_source`` is a zero-arg callable returning the engine's current
    step index (a host int read — zero device syncs)."""

    def __init__(self, step_source, capacity: int = 2048,
                 max_hops: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity {capacity} < 1")
        if max_hops < 8:
            raise ValueError(f"max_hops {max_hops} < 8")
        self._step_source = step_source
        self.capacity = capacity
        self.max_hops = max_hops
        self._journeys: OrderedDict[int, Journey] = OrderedDict()
        self.evicted = 0

    def begin(self, rid: int, tenant: str) -> Journey:
        """Create the journey for a new request (before the tracer stamps
        ``enqueued`` — the hook routes that event onto it). Evicts
        oldest-first TERMINAL journeys to stay under ``capacity``."""
        if len(self._journeys) >= self.capacity:
            for key in [k for k, j in self._journeys.items() if j.terminal]:
                if len(self._journeys) < self.capacity:
                    break
                del self._journeys[key]
                self.evicted += 1
        j = Journey(rid, tenant, self.max_hops)
        self._journeys[rid] = j
        return j

    def on_event(self, rid: int, name: str, t: float, args) -> None:
        """The Tracer ``journal`` hook: fold one lifecycle event into the
        request's journey. Unknown rids (journey evicted, or tracing
        began before the book) and non-hop events are ignored."""
        j = self._journeys.get(rid)
        if j is None:
            return
        kind = _EVENT_KINDS.get(name)
        if kind is None:
            return
        args = args or {}
        if kind == "enqueue":
            j.enqueued_t = t
        elif kind == "admit" and j.admitted_t is None:
            j.admitted_t = t
            j.prefix_hit_tokens = int(args.get("cached_tokens", 0))
        elif kind == "first_token" and j.first_token_t is None:
            j.first_token_t = t
        elif kind == "preempt":
            j.preemptions += 1
        elif kind == "retire":
            j.state = args.get("state")
            j.tokens = int(args.get("tokens", 0))
            j.retired_t = t
        j._hop(kind, self._step_source(), t, dict(args))

    def get(self, rid: int) -> Journey | None:
        return self._journeys.get(rid)

    def journeys(self) -> list[Journey]:
        """Every retained journey, oldest first."""
        return list(self._journeys.values())

    def wire_records(self, limit: int | None = None) -> list[dict]:
        """The newest ``limit`` journeys as wire dicts (all when None) —
        what the flight recorder embeds."""
        out = [j.to_wire() for j in self._journeys.values()]
        return out[-limit:] if limit is not None else out

    def __len__(self) -> int:
        return len(self._journeys)


def validate_journey(record) -> dict:
    """Schema gate for one wire journey: raises ValueError naming the
    first violation; returns the record for chaining."""
    if not isinstance(record, dict):
        raise ValueError(
            f"journey must be a dict, got {type(record).__name__}")
    if record.get("schema") != JOURNEY_SCHEMA:
        raise ValueError(f"unknown journey schema {record.get('schema')!r} "
                         f"(expected {JOURNEY_SCHEMA!r})")
    for key, typ in _WIRE_KEYS:
        if key not in record:
            raise ValueError(f"journey missing key {key!r}")
        if typ is int and isinstance(record[key], bool):
            raise ValueError(f"journey key {key!r} must be int, got bool")
        if not isinstance(record[key], typ):
            raise ValueError(f"journey key {key!r} must be {typ.__name__},"
                             f" got {type(record[key]).__name__}")
    state = record.get("state")
    if state is not None and not isinstance(state, str):
        raise ValueError(f"journey state must be str or None, got "
                         f"{type(state).__name__}")
    for key in _WIRE_LATENCIES:
        if key not in record:
            raise ValueError(f"journey missing key {key!r}")
        v = record[key]
        if v is not None and not isinstance(v, (int, float)):
            raise ValueError(f"journey key {key!r} must be a number or "
                             f"None, got {type(v).__name__}")
    for hop in record["hops"]:
        if not isinstance(hop, dict):
            raise ValueError(f"journey hop must be a dict: {hop!r}")
        for field in ("kind", "step", "t"):
            if field not in hop:
                raise ValueError(f"journey hop missing {field!r}: {hop}")
        if hop["kind"] not in JOURNEY_KINDS:
            raise ValueError(f"unknown journey hop kind {hop['kind']!r}")
    return record


def format_journey(record: dict) -> str:
    """Human-readable rendering of one (validated) wire journey — the
    CLI's ``--journey RID`` view: header, latency line, hop table."""
    def fmt(v):
        return f"{v:.6f}" if isinstance(v, (int, float)) else "-"

    lines = [f"journey rid={record['rid']} tenant={record['tenant']} "
             f"state={record['state'] or 'in-flight'} "
             f"tokens={record['tokens']} "
             f"preemptions={record['preemptions']}",
             f"queue_delay={fmt(record['queue_delay_s'])}s "
             f"ttft={fmt(record['ttft_s'])}s "
             f"tpot={fmt(record['tpot_s'])}s "
             f"e2e={fmt(record['e2e_s'])}s "
             f"prefix_hit_tokens={record['prefix_hit_tokens']}",
             f"hops ({len(record['hops'])}"
             + (f", {record['dropped_hops']} dropped" if
                record["dropped_hops"] else "") + "):"]
    for hop in record["hops"]:
        extra = ", ".join(f"{k}={v}" for k, v in sorted(hop.items())
                          if k not in ("kind", "step", "t"))
        lines.append(f"  step {hop['step']:>6} t={hop['t']:<12.6f} "
                     f"{hop['kind']:<14}" + (f" {extra}" if extra else ""))
    return "\n".join(lines)
