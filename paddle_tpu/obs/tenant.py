"""Per-tenant SLO classes and the goodput/badput ledger.

A **tenant** is a traffic class sharing one engine: "interactive" and
"batch" workloads with different TTFT/TPOT promises, or distinct
customers behind one deployment. This module declares the classes
(:class:`TenantSLO` — per-tenant p99 targets) and keeps the books
(:class:`TenantLedger`): every retirement is classified into exactly ONE
of the seven terminal classes

    in_slo     finished inside both targets (or no targets declared)
    ttft_late  finished, but time-to-first-token exceeded the target
    tpot_late  finished inside TTFT, but per-token time exceeded its target
    shed       dropped from a full queue before ever being admitted
    expired    retired by its deadline sweep
    cancelled  retired by engine.cancel()
    failed     retired by an injected or real step fault

and the request's emitted tokens accrue to that class — goodput is the
``in_slo`` token stream, badput everything else, and the per-class token
totals reconcile EXACTLY with the engine's ``serving_tokens_total``
(every emitted token lands in one class at retirement, including tokens
a recompute preemption re-emitted — both sides count the re-emission).

**Observe-only this PR**: the ledger classifies and accounts; weighted
admission by tenant stays with the fleet router (ROADMAP). The burn-rate
watchdog rule ``slo_burn`` (obs/alerts.py) windows the per-tenant
violation fraction the ledger exposes through
:meth:`TenantLedger.burn_totals` — host ints only.

SLO-violation semantics for the burn rate: ``ttft_late`` / ``tpot_late``
/ ``shed`` / ``expired`` / ``failed`` count as violations (the tenant
asked for work and the promise broke); ``cancelled`` does not (the
client withdrew), and ``in_slo`` obviously not.

Imports nothing from ``paddle_tpu.serving`` — serving imports us.
"""
from __future__ import annotations

from dataclasses import dataclass

__all__ = ["CLASSES", "TENANT_CLASSES", "VIOLATION_CLASSES", "TenantSLO",
           "TenantLedger", "check_tenant_name", "tenant_table"]

#: the seven terminal classes — the pre-seeded label set of
#: ``serving_tenant_retired_total{tenant=,class=}``
CLASSES = ("in_slo", "ttft_late", "tpot_late", "shed", "expired",
           "cancelled", "failed")
TENANT_CLASSES = CLASSES  # the package-level export name

#: classes the slo_burn watchdog counts as SLO violations
VIOLATION_CLASSES = frozenset(
    {"ttft_late", "tpot_late", "shed", "expired", "failed"})

# tenant names become metric-registry label values (``{tenant=<name>}``
# keys) and Chrome track names — the registry-key convention reserves
# ``{ } , =`` and quotes, so names are confined to a safe identifier set
_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_.-")


def check_tenant_name(name) -> str:
    """Validate a tenant name for use as a metric label value; returns
    it. Raises ValueError on anything that would corrupt the
    ``base{tenant=value}`` registry-key convention."""
    if not isinstance(name, str) or not name:
        raise ValueError(f"tenant name must be a non-empty str, got "
                         f"{name!r}")
    if len(name) > 64:
        raise ValueError(f"tenant name {name[:20]!r}... exceeds 64 chars")
    bad = set(name) - _NAME_OK
    if bad:
        raise ValueError(
            f"tenant name {name!r} contains {sorted(bad)} — allowed: "
            f"letters, digits, '_', '.', '-' (names become metric label "
            f"values and Chrome track names)")
    return name


@dataclass(frozen=True)
class TenantSLO:
    """One tenant class's latency promise: p99 targets for time-to-first-
    token and per-output-token time, in engine-clock seconds."""
    ttft_p99_s: float
    tpot_p99_s: float

    def validate(self) -> None:
        for field in ("ttft_p99_s", "tpot_p99_s"):
            v = getattr(self, field)
            if not (isinstance(v, (int, float)) and v > 0):
                raise ValueError(f"TenantSLO.{field} must be > 0, "
                                 f"got {v!r}")


class TenantLedger:
    """The per-tenant books: classification + token accrual per class.
    Pure host state (dicts of ints) — the engine feeds it once per
    retirement and the watchdog reads the monotonic totals."""

    def __init__(self, slos: dict | None = None):
        self.slos: dict[str, TenantSLO] = dict(slos or {})
        for name, slo in self.slos.items():
            check_tenant_name(name)
            slo.validate()
        # tenant -> {"retired": {class: n}, "tokens": {class: n}}
        self._books: dict[str, dict] = {}
        self.ensure("default")
        for name in self.slos:
            self.ensure(name)

    def ensure(self, tenant: str) -> None:
        """Open the (zeroed) books for a tenant."""
        if tenant not in self._books:
            self._books[tenant] = {
                "retired": {c: 0 for c in CLASSES},
                "tokens": {c: 0 for c in CLASSES},
            }

    def tenants(self) -> list[str]:
        """Every tenant with open books, declared-first order."""
        return list(self._books)

    def classify(self, tenant: str, state: str, ttft, tpot) -> str:
        """The terminal class of one retirement. Non-finished states map
        to their own class; a finished request checks the tenant's
        targets (no declared SLO — including the implicit ``default``
        tenant — finishes ``in_slo``)."""
        if state != "finished":
            if state not in CLASSES:
                raise ValueError(f"unknown terminal state {state!r}")
            return state
        slo = self.slos.get(tenant)
        if slo is None:
            return "in_slo"
        if ttft is not None and ttft > slo.ttft_p99_s:
            return "ttft_late"
        if tpot is not None and tpot > slo.tpot_p99_s:
            return "tpot_late"
        return "in_slo"

    def on_retire(self, tenant: str, state: str, ttft, tpot,
                  tokens: int) -> str:
        """Account one retirement: classify, bump the class's retirement
        count, accrue its emitted tokens. Returns the class."""
        self.ensure(tenant)
        cls = self.classify(tenant, state, ttft, tpot)
        book = self._books[tenant]
        book["retired"][cls] += 1
        book["tokens"][cls] += int(tokens)
        return cls

    # ----------------------------------------------------------- read side
    def burn_totals(self) -> dict[str, tuple[int, int]]:
        """{tenant: (violation retirements, total retirements)} — the
        monotonic host ints the slo_burn watchdog windows over."""
        out = {}
        for tenant, book in self._books.items():
            retired = book["retired"]
            total = sum(retired.values())
            violations = sum(retired[c] for c in CLASSES
                             if c in VIOLATION_CLASSES)
            out[tenant] = (violations, total)
        return out

    def token_totals(self) -> dict[str, dict[str, int]]:
        """{tenant: {class: tokens}} — the reconciliation surface: summed
        over everything, equals every emitted token of every RETIRED
        request, each counted exactly once."""
        return {t: dict(b["tokens"]) for t, b in self._books.items()}

    def rollup(self, hists: dict | None = None) -> dict:
        """The per-tenant flight-record section: class counts, token
        totals, goodput fraction, declared targets, and (when the caller
        passes the serving histogram families) observed p99s."""
        out = {}
        for tenant, book in self._books.items():
            tokens = book["tokens"]
            good = tokens["in_slo"]
            bad = sum(v for c, v in tokens.items() if c != "in_slo")
            entry = {
                "retired": dict(book["retired"]),
                "tokens": dict(tokens),
                "goodput_tokens": good,
                "badput_tokens": bad,
                "goodput_fraction": good / (good + bad)
                if good + bad else None,
            }
            slo = self.slos.get(tenant)
            if slo is not None:
                entry["slo"] = {"ttft_p99_s": slo.ttft_p99_s,
                                "tpot_p99_s": slo.tpot_p99_s}
            for key, fam in (hists or {}).items():
                child = fam.children().get(tenant)
                if child is not None:
                    entry[f"{key}_p99"] = child.percentile(0.99)
            out[tenant] = entry
        return out


def tenant_table(tenants: dict, header: bool = True) -> str:
    """Fixed-width per-tenant table from a rollup (live or out of a
    flight record): goodput %, observed TTFT/TPOT p99, and the badput
    breakdown by class — the CLI's ``--tenant-table`` view."""
    def pct(v):
        return f"{100.0 * v:>7.1f}%" if isinstance(v, (int, float)) \
            else f"{'-':>8}"

    def sec(v):
        return f"{v:>10.4f}" if isinstance(v, (int, float)) \
            else f"{'-':>10}"

    rows = []
    if header:
        rows.append(f"{'tenant':>12} {'goodput':>8} {'tokens':>8} "
                    f"{'ttft_p99':>10} {'tpot_p99':>10}  badput breakdown")
    for name in sorted(tenants):
        e = tenants[name]
        tokens = e.get("tokens", {})
        bad = ", ".join(f"{c}={tokens[c]}" for c in CLASSES
                        if c != "in_slo" and tokens.get(c))
        retired = e.get("retired", {})
        bad_retired = ", ".join(
            f"{c}:{retired[c]}" for c in CLASSES
            if c != "in_slo" and retired.get(c))
        breakdown = bad or bad_retired or "-"
        total = sum(tokens.values()) if tokens else 0
        rows.append(f"{name:>12} {pct(e.get('goodput_fraction'))} "
                    f"{total:>8} {sec(e.get('ttft_s_p99'))} "
                    f"{sec(e.get('tpot_s_p99'))}  {breakdown}")
    return "\n".join(rows)
