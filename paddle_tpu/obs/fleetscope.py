"""fleetscope — fleet-grain observability: cross-replica spans,
merged metrics, and the cluster flight recorder.

PRs 16-17 stopped the observability stack at the replica boundary: a
page fetch that retried three times through a half-open breaker was
one ``wire_retry`` journey hop and a global byte counter. This module
is the fleet-grain layer the ROADMAP's multi-host item will scrape
once real sockets land — three pieces:

- **Spans** (:class:`FleetScope`): every ``Transport.exchange``
  becomes a causally-linked span. The id is deterministic —
  :func:`span_id` is FNV-1a over (rid, hop serial), the same idiom as
  ``channel.unit_hash`` — and rides the wire in the v1-compatible
  payload tail (``wire._span_tail``), so the receiving side of a real
  network could link its half without a clock in common. Retry
  attempts, backoff waits, and breaker transitions arrive as child
  spans from the transport; :func:`flow_events` renders the tree as
  Chrome ``ph:"s"/"f"`` flow arrows from the sender track to the
  receiver track.
- **Merged metrics** (:class:`FleetMetrics`): every replica's registry
  snapshot folded into ONE valid prometheus exposition with a
  ``replica=`` label on each sample — the same renderer
  (``export.prometheus_text`` / ``_label_str``) and the same
  one-``# TYPE``-per-base grouping as a single replica's scrape, and
  the same text whether fed live snapshots or a fleet record's dumped
  gauges.
- **Cluster flight recorder**: ``paddle-tpu/fleet-record/v1`` bundles
  per-replica flight records (each validated against the existing v2
  schema), router state, the bounded ring of recent exchanges with
  their span trees, and the merged alert history.
  :func:`validate_fleet_record` is the strict gate, mirroring
  ``recorder.validate_flight_record``.

Layering: this module imports NOTHING from ``paddle_tpu.serving``
(serving imports us) — which is why the FNV-1a constants are declared
locally instead of taken from ``channel.unit_hash``.
"""
from __future__ import annotations

import json
from collections import deque

from .export import _fmt, prometheus_text
from .histogram import split_labels
from .recorder import validate_flight_record

__all__ = ["FLEET_RECORD_SCHEMA", "FleetMetrics", "FleetScope",
           "build_fleet_record", "dump_fleet_record", "flow_events",
           "format_fleet_record", "format_span_tree", "span_id",
           "span_key",
           "validate_fleet_record"]

FLEET_RECORD_SCHEMA = "paddle-tpu/fleet-record/v1"

#: the chrome-trace thread id of each replica's wire lane (spans and
#: flow endpoints live here, off the step/phase lanes)
WIRE_TID = 77

# FNV-1a 64-bit (same constants as serving.channel.unit_hash, declared
# locally — see the layering note in the module docstring)
_FNV_SEED = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = (1 << 64) - 1


def span_id(rid, serial: int) -> int:
    """Deterministic 64-bit span id for one exchange: FNV-1a over
    (rid, hop serial). A rid-less exchange (gossip carries no request)
    hashes rid as -1; the serial alone keeps the id unique."""
    h = _FNV_SEED
    for v in (-1 if rid is None else int(rid), int(serial)):
        h ^= v & _MASK
        h = (h * _FNV_PRIME) & _MASK
    return h


def span_key(sid: int) -> str:
    """The rendered span id — fixed-width hex, because a 64-bit int
    does not survive a JSON round trip through a float53 viewer."""
    return f"{sid:016x}"


class FleetScope:
    """Bounded recorder of cross-replica exchange spans.

    The router opens a span per exchange (it knows kind / src / dst /
    rid), the transport appends retry / backoff / breaker children and
    ends it — both behind one ``is not None`` attribute check, the
    tracer-None idiom, so a detached scope costs nothing. Everything
    is plain dicts on the deterministic transport timeline: the ring
    drops into the fleet record as-is.
    """

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._open: dict[int, dict] = {}
        self._serial = 0

    # ------------------------------------------------------------ record
    def open(self, *, kind: str, src, dst=None, rid=None, step: int = 0,
             t: float = 0.0) -> int:
        """Begin one exchange span; returns the id the frames (and the
        transport's child spans) travel under."""
        self._serial += 1
        sid = span_id(rid, self._serial)
        rec = {"span": span_key(sid), "serial": self._serial,
               "kind": str(kind), "rid": rid, "src": src, "dst": dst,
               "step": int(step), "t0": float(t), "t1": float(t),
               "ok": None, "retries": 0, "children": []}
        self._open[sid] = rec
        self._ring.append(rec)
        return sid

    def child(self, span: int, kind: str, t0: float, t1: float,
              **args) -> None:
        """One child span (attempt / backoff / breaker) under an open
        exchange. Unknown ids (ring evicted) are dropped, not raised —
        this sits on the transport's per-attempt path."""
        rec = self._open.get(span)
        if rec is None:
            return
        rec["children"].append(
            {"kind": str(kind), "t0": float(t0), "t1": float(t1),
             **args})

    def end(self, span: int, *, t: float, ok, retries: int = 0) -> None:
        """Close an exchange span with its outcome."""
        rec = self._open.pop(span, None)
        if rec is None:
            return
        rec["t1"] = float(t)
        rec["ok"] = None if ok is None else bool(ok)
        rec["retries"] = int(retries)

    # ------------------------------------------------------------- query
    def records(self) -> list:
        """The exchange ring, oldest first (JSON-ready dicts)."""
        return list(self._ring)

    def spans_for(self, rid) -> list:
        """Every recorded exchange span for one request id."""
        return [r for r in self._ring if r["rid"] == rid]


# ------------------------------------------------------- chrome flows
def flow_events(records, *, transport_pid: int,
                time_scale: float = 1e6) -> list:
    """Chrome trace events for exchange spans: an ``X`` slice plus a
    flow-start (``ph:"s"``) on the sender's wire lane, the children
    nested under it, and a landing slice plus flow-finish (``ph:"f"``,
    ``bp:"e"``) on the receiver's wire lane — one gossip / fetch /
    re-home reads as a single arrowed tree across replica tracks.
    Replica index ``i`` maps to pid ``i + 1`` (the fleet's chrome
    export convention); a side with no replica (gossip lands on the
    router) falls back to the transport's own track."""
    out = []
    pids = set()
    for rec in records:
        src = rec.get("src")
        dst = rec.get("dst")
        src_pid = transport_pid if src is None else int(src) + 1
        dst_pid = transport_pid if dst is None else int(dst) + 1
        pids.update((src_pid, dst_pid))
        name = f"wire:{rec['kind']}"
        ts = rec["t0"] * time_scale
        dur = max(rec["t1"] - rec["t0"], 0.0) * time_scale
        args = {"span": rec["span"], "rid": rec["rid"],
                "ok": rec["ok"], "retries": rec["retries"]}
        out.append({"name": name, "cat": "wire", "ph": "X", "ts": ts,
                    "dur": dur, "pid": src_pid, "tid": WIRE_TID,
                    "args": args})
        for ch in rec["children"]:
            out.append({"name": f"wire:{ch['kind']}", "cat": "wire",
                        "ph": "X", "ts": ch["t0"] * time_scale,
                        "dur": max(ch["t1"] - ch["t0"], 0.0)
                        * time_scale,
                        "pid": src_pid, "tid": WIRE_TID,
                        "args": {k: v for k, v in ch.items()
                                 if k not in ("t0", "t1")}})
        out.append({"name": name, "cat": "wire", "ph": "s",
                    "id": rec["span"], "ts": ts, "pid": src_pid,
                    "tid": WIRE_TID})
        out.append({"name": f"{name} recv", "cat": "wire", "ph": "X",
                    "ts": ts + dur, "dur": 1.0, "pid": dst_pid,
                    "tid": WIRE_TID, "args": {"span": rec["span"]}})
        out.append({"name": name, "cat": "wire", "ph": "f", "bp": "e",
                    "id": rec["span"], "ts": ts + dur, "pid": dst_pid,
                    "tid": WIRE_TID})
    out.extend({"ph": "M", "name": "thread_name", "pid": pid,
                "tid": WIRE_TID, "args": {"name": "wire"}}
               for pid in sorted(pids))
    return out


# ---------------------------------------------------- merged metrics
class FleetMetrics:
    """Every replica's registry folded into one scrape.

    ``per_replica`` maps replica name -> stats dict (registry keys,
    ``base{label=value}`` style). The merge injects ``replica=`` into
    each sample's label set and renders through the same exposition
    pipeline as a single replica — so the fleet view is one valid
    document with one ``# TYPE`` per base, identical in shape whether
    the inputs are live snapshots or a dumped fleet record's gauges
    (:meth:`from_fleet_record`).
    """

    def __init__(self, per_replica: dict, types: dict | None = None):
        self.per_replica = {str(k): dict(v)
                            for k, v in per_replica.items()}
        self.types = dict(types or {})

    @classmethod
    def from_fleet_record(cls, record: dict,
                          types: dict | None = None) -> "FleetMetrics":
        """The dump path: one registry per bundled flight record."""
        return cls({i: rec.get("gauges", {})
                    for i, rec in enumerate(record.get("replicas", ()))},
                   types)

    def merged(self) -> dict:
        """One registry-style dict with ``replica=`` merged into every
        key's label set."""
        out = {}
        for rep, stats in self.per_replica.items():
            for name, val in stats.items():
                base, labels = split_labels(name)
                body = ",".join(
                    f"{k}={v}"
                    for k, v in (*labels.items(), ("replica", rep)))
                out[f"{base}{{{body}}}"] = val
        return out

    def prometheus(self) -> str:
        """The merged text exposition (scalars; histogram bucket series
        stay per-replica — their percentile mirrors merge here)."""
        return prometheus_text(self.merged(), (), self.types)


# ----------------------------------------------------- fleet record
_FLEET_KEYS = (("schema", str), ("reason", str), ("dumped_at", float),
               ("step", int), ("replicas", list), ("router", dict),
               ("exchanges", list), ("alerts", list))


def build_fleet_record(*, reason: str, now: float, step: int, replicas,
                       router: dict, exchanges, alerts) -> dict:
    """Assemble a fleet record (the cluster-grain counterpart of
    ``recorder.build_flight_record``): per-replica flight records,
    router state, the exchange-span ring, and the merged alert
    history."""
    return {"schema": FLEET_RECORD_SCHEMA, "reason": str(reason),
            "dumped_at": float(now), "step": int(step),
            "replicas": list(replicas), "router": dict(router),
            "exchanges": list(exchanges), "alerts": list(alerts)}


def dump_fleet_record(path, record: dict) -> dict:
    """Validate and write one fleet record as JSON; returns the
    record."""
    validate_fleet_record(record)
    with open(path, "w") as f:
        json.dump(record, f, indent=1, default=str)
    return record


def validate_fleet_record(record) -> dict:
    """The strict schema gate for ``paddle-tpu/fleet-record/v1`` —
    raises ValueError naming the first offending key; every bundled
    replica record must itself pass ``validate_flight_record``.
    Returns the record for chaining."""
    if not isinstance(record, dict):
        raise ValueError(f"fleet record must be a dict, "
                         f"got {type(record).__name__}")
    schema = record.get("schema")
    if schema != FLEET_RECORD_SCHEMA:
        raise ValueError(f"unknown fleet record schema {schema!r} "
                         f"(this build speaks {FLEET_RECORD_SCHEMA})")
    for key, typ in _FLEET_KEYS:
        if key not in record:
            raise ValueError(f"fleet record missing key {key!r}")
        v = record[key]
        if typ is float and isinstance(v, int) \
                and not isinstance(v, bool):
            v = float(v)  # JSON round-trips integral floats as ints
        if not isinstance(v, typ):
            raise ValueError(
                f"fleet record key {key!r} must be {typ.__name__}, "
                f"got {type(record[key]).__name__}")
    for i, rec in enumerate(record["replicas"]):
        try:
            validate_flight_record(rec)
        except ValueError as e:
            raise ValueError(f"fleet record replica {i}: {e}") from e
    for i, ex in enumerate(record["exchanges"]):
        if not isinstance(ex, dict) \
                or not {"span", "kind", "t0", "t1",
                        "children"} <= set(ex):
            raise ValueError(
                f"fleet record exchange {i} is not a span record")
    for i, al in enumerate(record["alerts"]):
        if not isinstance(al, dict) or "rule" not in al \
                or "replica" not in al:
            raise ValueError(
                f"fleet record alert {i} missing rule/replica")
    return record


# -------------------------------------------------------- formatting
def format_span_tree(rec: dict) -> str:
    """One exchange span and its children as an indented tree — the
    ``--span`` CLI view."""
    head = (f"span {rec['span']} wire:{rec['kind']} rid={rec['rid']} "
            f"src={rec['src']} dst={rec['dst']} step={rec['step']} "
            f"[{_fmt(rec['t0'])}s -> {_fmt(rec['t1'])}s] "
            f"ok={rec['ok']} retries={rec['retries']}")
    lines = [head]
    kids = rec.get("children", [])
    for i, ch in enumerate(kids):
        tee = "`-" if i == len(kids) - 1 else "|-"
        extra = " ".join(f"{k}={v}" for k, v in sorted(ch.items())
                         if k not in ("kind", "t0", "t1"))
        lines.append(f"  {tee} {ch['kind']} "
                     f"[{_fmt(ch['t0'])}s -> {_fmt(ch['t1'])}s]"
                     + (f" {extra}" if extra else ""))
    return "\n".join(lines)


def format_fleet_record(record: dict) -> str:
    """Human-readable summary: the per-replica roll-up table, breaker
    states, and the exchange-ring tally — the default ``--fleet-record``
    CLI view."""
    out = [f"fleet record {record['schema']} "
           f"reason={record['reason']!r} step={record['step']} "
           f"dumped_at={_fmt(record['dumped_at'])}s"]
    out.append(f"{'replica':>8} {'reason':>16} {'step':>6} "
               f"{'requests':>8} {'tokens':>8} {'alerts':>6}")
    for i, rec in enumerate(record["replicas"]):
        gauges = rec.get("gauges", {})
        out.append(f"{i:>8} {rec['reason'][:16]:>16} "
                   f"{rec['step']:>6} {len(rec['requests']):>8} "
                   f"{_fmt(gauges.get('serving_tokens_total', 0)):>8} "
                   f"{len(rec['alerts']):>6}")
    router = record["router"]
    breakers = router.get("breakers", {})
    if breakers:
        states = " ".join(f"peer {p}: {s}"
                          for p, s in sorted(breakers.items()))
        out.append(f"breakers: {states}")
    out.append(f"router: live={router.get('live')} "
               f"down={router.get('down')} "
               f"pending={len(router.get('pending', ()))} "
               f"weights={router.get('weights')}")
    out.append(f"exchanges: {len(record['exchanges'])} spans recorded, "
               f"{len(record['alerts'])} fleet alerts")
    return "\n".join(out)
