"""Engine-loop step timeline: a bounded ring of per-step records.

Per-request traces (obs/trace.py) answer "where did THIS request spend its
time"; the step timeline answers the complementary operational question —
"what was the ENGINE doing when tail latency spiked": how big was the
batch, how much of the step was prefill vs decode, how full was the page
pool, did anything get preempted, and (under ``debug_checks``) how many
host syncs the step paid. A ``deque(maxlen=capacity)`` keeps memory
bounded no matter how long the engine serves; the newest ``capacity``
steps are always available for export into the Chrome-trace engine track.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

__all__ = ["StepRecord", "StepTimeline"]


@dataclass(frozen=True)
class StepRecord:
    """One continuous-batching iteration, as the engine saw it."""
    step: int            # engine step index
    t_start: float       # engine-clock seconds
    t_end: float
    admitted: int        # requests admitted this step (incl. swap resumes)
    prefills: int        # prefills COMPLETED this step (first token out)
    batch: int           # active decode slots this step
    finished: int        # requests that finished this step
    preemptions: int     # victims preempted this step
    queue_depth: int     # waiting requests after the step
    pages_in_use: int    # pool pages held after the step
    chunks: int = 0      # chunked-prefill chunks executed this step
    accepted: int = 0    # speculative candidates accepted this step
    # (ServingConfig(spec=); tokens emitted = batch + accepted per step)
    host_syncs: int | None = None  # SyncTally count (debug_checks only)
    phase_s: dict = field(default_factory=dict)  # wall-time attribution:
    # {phase: seconds} over obs.attribution.PHASES — sums to duration
    # exactly (the PhaseAccumulator mark contract); {} with tracing off
    # or on pre-attribution records
    extra: dict = field(default_factory=dict)  # exporter passthrough

    @property
    def duration(self) -> float:
        return self.t_end - self.t_start

    def phase_mix(self) -> str:
        """Coarse label of what the step did — the field Perfetto colors
        the engine track by. A step that only advanced chunks (no prefill
        completed, nothing decoding yet) still reads "prefill"."""
        parts = []
        if self.prefills or self.chunks:
            parts.append("prefill")
        if self.batch:
            parts.append("decode")
        return "+".join(parts) or "idle"


class StepTimeline:
    """Ring buffer of :class:`StepRecord`. Appends are O(1); the deque
    drops the oldest record once ``capacity`` is reached."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity {capacity} < 1")
        self.capacity = capacity
        self._records: deque[StepRecord] = deque(maxlen=capacity)
        self.total_steps = 0  # appended ever, incl. records since dropped

    def append(self, record: StepRecord) -> None:
        self._records.append(record)
        self.total_steps += 1

    def records(self) -> list[StepRecord]:
        """Retained records, oldest first."""
        return list(self._records)

    @property
    def last(self) -> StepRecord | None:
        return self._records[-1] if self._records else None

    def __len__(self) -> int:
        return len(self._records)
