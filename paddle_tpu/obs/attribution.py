"""Goodput attribution: where does an engine step's wall time actually go.

Two host-only instruments, closing the static->runtime loop the analysis
layer left open. hlocheck (PR 6) freezes an analytic cost model — flops
and peak HBM bytes — for every compiled program, and kernelcheck (PR 11)
banks a predicted speedup for every Pallas kernel; nothing ever compared
those predictions to measured wall time. This module does, off the
pluggable engine clock, with ZERO device syncs added (clock reads only —
the SyncTally decode-loop certification is byte-identical with
attribution on):

- :class:`PhaseAccumulator` — splits one step's wall time across the
  phases the step actually ran (admit/restore, swap resume, prefill,
  chunked prefill, decode-or-verify, eviction/preemption, residual
  "other") by stamping a mark at each phase boundary. The interval since
  the previous mark is charged to the named phase, so the per-phase
  times SUM EXACTLY to the step's wall time by construction — no
  sampling, no double counting. The engine rolls the split into the
  ``serving_step_phase_s{phase=}`` histogram family and onto each
  :class:`~paddle_tpu.obs.timeline.StepRecord`.
- :class:`RooflineTracker` — accumulates measured per-program dispatch
  times (each engine dispatch site times dispatch -> sanctioned fetch,
  so device time is included via the fetch's block) against the
  predictions the engine's own first-trace hlocheck audits already hold
  (NO second lowering), and publishes:

  * ``serving_mfu`` — achieved flops/s over the audited programs'
    measured time, divided by the device peak,
  * ``serving_hbm_bw_util`` — same for the audits' HBM byte roll-up
    against peak memory bandwidth,
  * ``serving_cost_model_drift{program=}`` — measured mean step time /
    roofline-predicted time (``max(flops/peak_flops, bytes/peak_bw)``)
    per compiled program, kept as a high-watermark — the live answer to
    "is the analytic cost model still telling the truth",
  * ``serving_kernel_speedup_{predicted,measured,drift}{kernel=}`` —
    kernelcheck's banked predicted speedup beside the measured
    composite/kernel dispatch-time ratio whenever a Pallas kernel
    actually serves traffic, so the on-chip A/B the ROADMAP demands is a
    gauge read, not a bespoke experiment.

Peaks default to TPU v5e (the generation kernelcheck's VMEM caps are
certified against); override per deployment via
``ServingConfig(peak_flops_per_s=, peak_hbm_bytes_per_s=)``. On CPU the
absolute MFU number is nonsense-but-stable — drift ratios and phase
attribution remain meaningful, which is what the tests pin.

Imports nothing from ``paddle_tpu.serving`` (serving imports us) and
touches no device state.
"""
from __future__ import annotations

__all__ = ["PHASES", "PhaseAccumulator", "RooflineTracker",
           "DEFAULT_PEAK_FLOPS_PER_S", "DEFAULT_PEAK_HBM_BYTES_PER_S",
           "load_banked_kernel_speedups"]

#: the phase vocabulary — the pre-seeded label set of the
#: ``serving_step_phase_s{phase=}`` histogram family. "admit" covers the
#: deadline sweep + scheduler admission (including host-tier restores),
#: "swap" the swap-resume re-entry, "evict" injected/real preemption and
#: decode-page eviction pressure, "other" the residual step bookkeeping.
PHASES = ("admit", "swap", "prefill", "chunk_prefill", "decode", "verify",
          "evict", "other")

# TPU v5e: ~197 TFLOP/s bf16 and ~819 GB/s HBM per chip — the same
# generation kernelcheck's VMEM caps are certified at. Deployments on
# other parts override via ServingConfig.
DEFAULT_PEAK_FLOPS_PER_S = 1.97e14
DEFAULT_PEAK_HBM_BYTES_PER_S = 8.19e11


class PhaseAccumulator:
    """Mark-based wall-time splitter for one engine step at a time.

    ``begin(t)`` opens a step; each ``mark(phase)`` charges the interval
    since the previous mark (or begin) to ``phase`` and returns it;
    ``finish()`` charges the remainder to ``"other"`` and returns
    ``(t_end, {phase: seconds})``. Exactness contract: the returned
    phase dict's values are precisely the consecutive clock deltas, so
    on any clock they sum to ``t_end - t_begin`` up to float addition —
    and EXACTLY on the integer-valued virtual clocks the tests use.
    """

    __slots__ = ("_clock", "open", "t0", "_last", "_acc")

    def __init__(self, clock):
        self._clock = clock
        self.open = False
        self.t0 = 0.0
        self._last = 0.0
        self._acc: dict[str, float] = {}

    def begin(self, t: float | None = None) -> float:
        t = self._clock() if t is None else t
        self.open = True
        self.t0 = self._last = t
        self._acc = {}
        return t

    def mark(self, phase: str, t: float | None = None) -> float:
        """Charge now - last_mark to ``phase``; returns the interval."""
        t = self._clock() if t is None else t
        dt = t - self._last
        if dt:
            self._acc[phase] = self._acc.get(phase, 0.0) + dt
        self._last = t
        return dt

    def finish(self, t: float | None = None) -> tuple[float, dict]:
        """Close the step: residual time goes to ``"other"``; returns
        ``(t_end, phases)``."""
        t = self._clock() if t is None else t
        self.mark("other", t)
        self.open = False
        return t, self._acc


def load_banked_kernel_speedups() -> dict[str, float]:
    """kernelcheck's banked ``predicted_speedup`` per kernel, from
    ``profiles/kernelcheck.json`` — {} when the bank (or the analysis
    package) is unavailable, so obs never hard-depends on it."""
    try:
        import json

        from ..analysis.kernelcheck import bank_path

        with open(bank_path()) as fh:
            banked = json.load(fh)
    except Exception:  # noqa: BLE001 — optional input, absence is normal
        return {}
    return {name: rec["predicted_speedup"]
            for name, rec in banked.items()
            if isinstance(rec, dict)
            and isinstance(rec.get("predicted_speedup"), (int, float))}


class RooflineTracker:
    """Measured-vs-predicted accounting per compiled program.

    Predictions arrive once per program from the engine's first-trace
    hlocheck audit (``on_program``); measurements accrue per dispatch
    (``on_call`` — dispatch-to-fetch wall seconds). ``publish`` pushes
    the derived gauges through a ``ServingMetrics`` and is a no-op until
    both sides of at least one program exist, so a non-debug engine
    (no audits) pays one boolean check per step.
    """

    def __init__(self, peak_flops_per_s: float = 0.0,
                 peak_hbm_bytes_per_s: float = 0.0,
                 banked_kernels: dict[str, float] | None = None):
        self.peak_flops = float(peak_flops_per_s) or DEFAULT_PEAK_FLOPS_PER_S
        self.peak_bw = (float(peak_hbm_bytes_per_s)
                        or DEFAULT_PEAK_HBM_BYTES_PER_S)
        if self.peak_flops <= 0 or self.peak_bw <= 0:
            raise ValueError(
                f"device peaks must be positive, got flops/s "
                f"{self.peak_flops}, bytes/s {self.peak_bw}")
        # label -> (flops, hbm_bytes) predicted per step of this program
        self._predicted: dict[str, tuple[float, float]] = {}
        # label -> [seconds, calls] measured
        self._measured: dict[str, list[float]] = {}
        # kernel A/B: name -> banked predicted speedup; measured split by
        # which path served the dispatch
        self._kernel_predicted = dict(banked_kernels or {})
        self._kernel_s: dict[str, list[float]] = {}  # [k_s, k_n, c_s, c_n]
        self._dirty = False

    # ------------------------------------------------------------- feeding
    def on_program(self, label: str, flops: float, hbm_bytes: float) -> None:
        """One hlocheck audit's analytic roll-up for a compiled program."""
        self._predicted[label] = (float(flops), float(hbm_bytes))

    def on_call(self, label: str, seconds: float) -> None:
        """One measured dispatch of ``label`` (dispatch -> fetch wall)."""
        acc = self._measured.get(label)
        if acc is None:
            acc = self._measured[label] = [0.0, 0]
        acc[0] += seconds
        acc[1] += 1
        if label in self._predicted:
            self._dirty = True

    def on_kernel_call(self, name: str, seconds: float,
                       pallas: bool) -> None:
        """One measured dispatch of a kernel-eligible step: ``pallas``
        says whether the Pallas kernel (True) or the composite fallback
        path (False) served it."""
        acc = self._kernel_s.get(name)
        if acc is None:
            acc = self._kernel_s[name] = [0.0, 0, 0.0, 0]
        i = 0 if pallas else 2
        acc[i] += seconds
        acc[i + 1] += 1
        # a sample only moves a published gauge once BOTH legs have been
        # measured (the A/B ratio); the banked predicted gauges are
        # published at engine construction, so a one-legged steady state
        # (every dispatch on the same path) keeps publish() a no-op
        if acc[1] and acc[3]:
            self._dirty = True

    # ------------------------------------------------------------ deriving
    def predicted_step_s(self, label: str) -> float | None:
        """The roofline time for one step of ``label``: whichever of
        compute and memory traffic binds at the configured peaks."""
        pred = self._predicted.get(label)
        if pred is None:
            return None
        flops, nbytes = pred
        return max(flops / self.peak_flops, nbytes / self.peak_bw)

    def gauges(self) -> dict:
        """The derived gauge values:

        - ``mfu`` / ``hbm_bw_util``: achieved/(peak) over every program
          with both a prediction and measured time,
        - ``drift``: {label: measured mean / predicted} per such program,
        - ``kernels``: {name: {predicted, measured, drift}} — measured
          present only once BOTH dispatch paths have samples.
        """
        flops = nbytes = seconds = 0.0
        drift: dict[str, float] = {}
        for label, (s, n) in self._measured.items():
            pred_s = self.predicted_step_s(label)
            if pred_s is None or not n or s <= 0:
                continue
            f, b = self._predicted[label]
            flops += f * n
            nbytes += b * n
            seconds += s
            if pred_s > 0:
                drift[label] = (s / n) / pred_s
        out = {
            "mfu": flops / seconds / self.peak_flops if seconds else 0.0,
            "hbm_bw_util": (nbytes / seconds / self.peak_bw
                            if seconds else 0.0),
            "drift": drift,
            "kernels": {},
        }
        for name in {*self._kernel_predicted, *self._kernel_s}:
            predicted = self._kernel_predicted.get(name)
            entry: dict = {}
            if predicted is not None:
                entry["predicted"] = predicted
            acc = self._kernel_s.get(name)
            if acc and acc[1] and acc[3] and acc[0] > 0:
                measured = (acc[2] / acc[3]) / (acc[0] / acc[1])
                entry["measured"] = measured
                if predicted:
                    entry["drift"] = measured / predicted
            out["kernels"][name] = entry
        return out

    def publish(self, metrics) -> None:
        """Push the gauges through a ``ServingMetrics``. No-op (one
        boolean check) unless new measurements landed since the last
        publish."""
        if not self._dirty:
            return
        self._dirty = False
        g = self.gauges()
        metrics.on_roofline(g["mfu"], g["hbm_bw_util"])
        for label, ratio in g["drift"].items():
            metrics.on_drift(label, ratio)
        for name, entry in g["kernels"].items():
            metrics.on_kernel_ab(name, predicted=entry.get("predicted"),
                                 measured=entry.get("measured"),
                                 drift=entry.get("drift"))
