"""Exporters: Chrome ``trace_event`` JSON (Perfetto) and Prometheus text.

Chrome trace: the classic ``{"traceEvents": [...]}`` JSON that
chrome://tracing and https://ui.perfetto.dev load directly. Layout is one
process (pid 1, "paddle_tpu.serving") holding one track per request (tid =
rid + 1, named "request <rid>") plus the engine loop on tid 0: request
tracks carry complete ("X") spans for the queued / prefill / decode phases
rebuilt from the raw lifecycle events, with instants ("i") for
preemptions, swaps, decode marks, and retirement; the engine track carries
one span per step, labeled by its phase mix and carrying the step's batch
size / page pressure / preemption count / phase attribution in ``args``,
with a global instant per watchdog alert. Counter tracks (``ph: "C"`` —
Perfetto renders them as stacked area charts above the spans) plot
``pages_in_use`` / ``batch`` / ``queue_depth`` per step from the timeline
ring, so resource pressure is visible alongside the request spans it
explains, and each tenant with retired journeys gets its own track of
retirement instants. Timestamps are engine-clock seconds rebased to the
earliest event and scaled to the microseconds the format requires — a
virtual test clock exports exactly like a wall clock.

Prometheus: standard text exposition (``# TYPE`` + samples) over the
monitor registry's ``serving_*`` scalars and the obs histograms rendered
as cumulative ``_bucket{le="..."}`` series with ``_sum``/``_count`` — the
format every Prometheus scraper and promtool understands. Labeled
family members — registry keys shaped ``base{label=value}`` (one or
more labels), e.g. ``serving_alerts_total{rule=queue_stall}``, the
``serving_step_phase_s{phase=}`` / ``serving_ttft_s{tenant=}``
histogram children, and the multi-label
``serving_tenant_retired_total{tenant=,class=}`` counters — render as
one metric family per base through the one label-set renderer
(:func:`_label_str`: sorted ``k="v"`` pairs, escaped values), so a
family bucket like ``serving_ttft_s_bucket{le="0.5",tenant="batch"}``
is identical text on the live-registry and flight-record-dump paths.
"""
from __future__ import annotations

import json

from .histogram import Histogram, split_labels
from .timeline import StepTimeline
from .trace import RequestTrace

__all__ = ["chrome_trace", "write_chrome_trace", "prometheus_text",
           "latency_table"]

_ENGINE_TID = 0
_PID = 1

# lifecycle events that ALSO render as instants on the request's track
_INSTANTS = ("pallas_fallback",
             "preempted", "swap_out", "swap_in", "decode_mark",
             "prefill_chunk", "retired", "spill", "restore",
             "spec_verify",
             "wire_retry", "refetch_fallback", "breaker_open")


def _request_events(trace: RequestTrace) -> list[dict]:
    """Rebuild one request's phase spans + instants from its raw events.
    A span left open at the end of the trace (a still-live request) is
    closed at the last event's timestamp so exports of a running engine
    stay loadable."""
    tid = trace.rid + 1
    out: list[dict] = []
    open_name: str | None = None
    open_t = 0.0

    def close(t: float) -> None:
        nonlocal open_name
        if open_name is not None:
            out.append({"name": open_name, "ph": "X", "ts": open_t,
                        "dur": max(t - open_t, 0.0), "pid": _PID,
                        "tid": tid, "cat": "request"})
            open_name = None

    for ev in trace.events:
        if ev.name == "enqueued":
            open_name, open_t = "queued", ev.t
        elif ev.name == "admitted":
            close(ev.t)
        elif ev.name == "prefill_start":
            close(ev.t)
            open_name, open_t = "prefill", ev.t
        elif ev.name == "prefill_chunk":
            # chunked prefill: each chunk gets its own span on the track
            # (the first closes the opening "prefill" sliver, later ones
            # close their predecessor) — chunk boundaries stay visible
            close(ev.t)
            open_name, open_t = "prefill_chunk", ev.t
        elif ev.name == "prefill_end":
            close(ev.t)
        elif ev.name in ("first_token", "resumed"):
            close(ev.t)
            open_name, open_t = "decode", ev.t
        elif ev.name == "preempted":
            close(ev.t)
            open_name, open_t = "queued", ev.t
        elif ev.name == "retired":
            close(ev.t)
        if ev.name in _INSTANTS:
            name = ev.name
            if ev.name == "retired":
                name = f"retired: {ev.arg('state', '?')}"
            out.append({"name": name, "ph": "i", "ts": ev.t, "pid": _PID,
                        "tid": tid, "s": "t", "cat": "request",
                        "args": dict(ev.args or {})})
    if trace.events:
        close(trace.events[-1].t)
    return out


# the per-step counter tracks: (track name, StepRecord attribute) —
# Perfetto plots each as an area chart above the spans, so page pressure
# and queue depth are visible against the request activity they explain
_COUNTER_TRACKS = (("pages_in_use", "pages_in_use"), ("batch", "batch"),
                   ("queue_depth", "queue_depth"))


#: tenant tracks sit far above any plausible request tid (tid = rid + 1)
_TENANT_TID_BASE = 1_000_000


def chrome_trace(traces=(), timeline: StepTimeline | None = None,
                 alerts=(), journeys=()) -> dict:
    """Build the ``trace_event`` JSON dict from request traces, the
    engine step timeline, the watchdog alert history, and/or the
    journey book — each tenant with retired journeys gets its own track
    of retirement instants (state + token count + latency summary), so
    per-tenant traffic reads alongside the per-request spans. Accepts
    :class:`~paddle_tpu.obs.journey.Journey` objects or their wire
    dicts. Pure function of its inputs — safe to call on a live engine
    between steps."""
    raw: list[dict] = []
    names: dict[int, str] = {_ENGINE_TID: "engine loop"}
    for trace in traces:
        names[trace.rid + 1] = f"request {trace.rid}"
        raw.extend(_request_events(trace))
    tenant_tids: dict[str, int] = {}
    for j in journeys:
        w = j if isinstance(j, dict) else j.to_wire()
        if w.get("state") is None or w.get("e2e_s") is None:
            continue  # still in flight: its request track tells the story
        tid = tenant_tids.get(w["tenant"])
        if tid is None:
            tid = _TENANT_TID_BASE + len(tenant_tids)
            tenant_tids[w["tenant"]] = tid
            names[tid] = f"tenant {w['tenant']}"
        retire_t = next((h["t"] for h in reversed(w["hops"])
                         if h["kind"] == "retire"), None)
        if retire_t is None:
            continue
        raw.append({"name": f"retire:{w['state']}", "ph": "i",
                    "ts": retire_t, "pid": _PID, "tid": tid, "s": "t",
                    "cat": "tenant",
                    "args": {"rid": w["rid"], "tokens": w["tokens"],
                             "ttft_s": w["ttft_s"], "tpot_s": w["tpot_s"],
                             "e2e_s": w["e2e_s"]}})
    if timeline is not None:
        for rec in timeline.records():
            args = {"step": rec.step, "batch": rec.batch,
                    "prefills": rec.prefills, "chunks": rec.chunks,
                    "admitted": rec.admitted,
                    "finished": rec.finished,
                    "preemptions": rec.preemptions,
                    "queue_depth": rec.queue_depth,
                    "pages_in_use": rec.pages_in_use}
            if rec.accepted:
                # speculative decoding: candidates the verify accepted
                # (tokens this step = batch + accepted)
                args["accepted"] = rec.accepted
            if rec.host_syncs is not None:
                args["host_syncs"] = rec.host_syncs
            if rec.phase_s:
                args["phases"] = dict(rec.phase_s)
            args.update(rec.extra)
            raw.append({"name": rec.phase_mix(), "ph": "X",
                        "ts": rec.t_start, "dur": rec.duration,
                        "pid": _PID, "tid": _ENGINE_TID, "cat": "engine",
                        "args": args})
            for track, attr in _COUNTER_TRACKS:
                raw.append({"name": track, "ph": "C", "ts": rec.t_end,
                            "pid": _PID, "tid": _ENGINE_TID,
                            "cat": "engine",
                            "args": {track: getattr(rec, attr)}})
    for alert in alerts:
        a = alert if isinstance(alert, dict) else alert.asdict()
        raw.append({"name": f"alert:{a['rule']}", "ph": "i", "ts": a["t"],
                    "pid": _PID, "tid": _ENGINE_TID, "s": "g",
                    "cat": "alert",
                    "args": {"step": a["step"], "message": a["message"],
                             **(a.get("data") or {})}})
    # rebase to the earliest timestamp and scale seconds -> microseconds
    origin = min((e["ts"] for e in raw), default=0.0)
    for e in raw:
        e["ts"] = (e["ts"] - origin) * 1e6
        if "dur" in e:
            e["dur"] *= 1e6
    meta = [{"name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
             "args": {"name": "paddle_tpu.serving"}}]
    meta += [{"name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
              "args": {"name": label}}
             for tid, label in sorted(names.items())]
    return {"traceEvents": meta + raw, "displayTimeUnit": "ms"}


def write_chrome_trace(path, traces=(),
                       timeline: StepTimeline | None = None,
                       alerts=(), journeys=()) -> dict:
    """Render and write the Perfetto-loadable JSON; returns the dict."""
    doc = chrome_trace(traces, timeline, alerts, journeys)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def _fmt(v) -> str:
    """Prometheus sample value: integral floats print as ints."""
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _escape_label(v) -> str:
    """Prometheus label-value escaping: backslash, double-quote, and
    newline must be escaped inside the quoted value (the exposition
    format's only three specials)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _label_str(labels: dict) -> str:
    """The one label-set renderer behind every exposition sample:
    ``{k="v",k2="v2"}`` with the pairs SORTED by key and the values
    escaped — so a multi-label sample (a histogram-family bucket's
    merged ``{tenant=, le=}``, a ``{tenant=, class=}`` counter) renders
    the same valid text regardless of which path assembled the dict.
    Empty string for no labels."""
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(v)}"'
                          for k, v in sorted(labels.items())) + "}"


def prometheus_text(stats: dict, histograms=(), types: dict | None = None,
                    ) -> str:
    """Text exposition of scalar stats (``types`` maps BASE name ->
    "counter"; everything else is a gauge) plus histograms as cumulative
    bucket series. Histogram-derived scalar mirrors (``<hist>_p50`` etc.)
    are skipped — scrapers should aggregate the buckets themselves.
    Registry keys shaped ``base{label=value}`` (the labeled-family
    convention) render as one metric family per base with proper sample
    labels; sorted key order keeps each family's samples contiguous, so
    the ``# TYPE`` header is emitted once per base."""
    types = types or {}
    lines: list[str] = []
    hist_bases = tuple({split_labels(h.name)[0] for h in histograms})
    last_typed = None
    for name in sorted(stats):
        base, labels = split_labels(name)
        if base.startswith(hist_bases) and hist_bases:
            continue  # published as a real histogram below
        if base != last_typed:
            lines.append(f"# TYPE {base} {types.get(base, 'gauge')}")
            last_typed = base
        lines.append(f"{base}{_label_str(labels)} {_fmt(stats[name])}")
    for h in histograms:
        base, labels = split_labels(h.name)
        if base != last_typed:
            lines.append(f"# TYPE {base} histogram")
            last_typed = base
        for edge, cum in h.cumulative_buckets():
            le = "+Inf" if edge == float("inf") else f"{edge:.10g}"
            lines.append(f"{base}_bucket"
                         f"{_label_str(dict(labels, le=le))} {cum}")
        lines.append(f"{base}_sum{_label_str(labels)} {_fmt(h.sum)}")
        lines.append(f"{base}_count{_label_str(labels)} {h.count}")
    return "\n".join(lines) + "\n"


def latency_table(summaries, header: bool = True) -> str:
    """Fixed-width per-request latency table (queue wait / TTFT / TPOT /
    e2e, seconds) from :meth:`RequestTrace.summary` dicts — the demo's
    human-readable view of the same decomposition the histograms
    aggregate."""
    def cell(v, width=10):
        return (f"{v:>{width}.4f}" if isinstance(v, float)
                else f"{str(v) if v is not None else '-':>{width}}")

    rows = []
    if header:
        rows.append(f"{'rid':>5} {'state':>9} {'tokens':>6} "
                    f"{'queue_wait':>10} {'ttft':>10} {'tpot':>10} "
                    f"{'e2e':>10}")
    for s in summaries:
        rows.append(" ".join([f"{s['rid']:>5}", f"{s['state'] or '?':>9}",
                              f"{s['tokens']:>6}",
                              cell(s["queue_wait"]), cell(s["ttft"]),
                              cell(s["tpot"]), cell(s["e2e"])]))
    return "\n".join(rows)
