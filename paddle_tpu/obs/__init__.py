"""paddle_tpu.obs — serving-grade observability.

The layer that answers the operational questions the serving invariants
(compile-once, sync-free decode — paddle_tpu.analysis) cannot: where did a
request spend its time, what are TTFT/TPOT at p50/p99, and what did the
engine's step timeline look like when tail latency spiked.

- :mod:`~paddle_tpu.obs.trace` — per-request lifecycle traces
  (:class:`Tracer`, :class:`RequestTrace`): timestamped events from the
  pluggable engine clock, summarized into queue_wait / prefill_time /
  TTFT / TPOT / e2e. O(1) per event, bounded retention.
- :mod:`~paddle_tpu.obs.histogram` — fixed-bucket streaming
  :class:`Histogram` (bounded memory, pre-seeded presence) backing the
  ``serving_ttft_s`` / ``serving_tpot_s`` / ``serving_queue_wait_s`` /
  ``serving_e2e_s`` / ``serving_step_duration_s`` /
  ``serving_batch_occupancy`` percentile gauges.
- :mod:`~paddle_tpu.obs.timeline` — the engine loop's bounded per-step
  ring (:class:`StepTimeline`): phase mix, batch size, page pressure,
  preemptions, host syncs under ``debug_checks``.
- :mod:`~paddle_tpu.obs.export` — Chrome ``trace_event`` JSON (one track
  per request + one for the engine loop; loads in Perfetto) and
  Prometheus text exposition.

Imports nothing from ``paddle_tpu.serving`` — serving imports us. Tracing
is on by default in the engine (``ServingConfig(enable_tracing=)``); the
off path costs one attribute check per event site and the on path adds no
host syncs to the decode loop (the SyncTally certification is unchanged).
"""
from .export import (chrome_trace, latency_table,  # noqa: F401
                     prometheus_text, write_chrome_trace)
from .histogram import (LATENCY_EDGES_S, OCCUPANCY_EDGES,  # noqa: F401
                        QUANTILES, Histogram)
from .timeline import StepRecord, StepTimeline  # noqa: F401
from .trace import RequestTrace, TraceEvent, Tracer  # noqa: F401

__all__ = ["Histogram", "LATENCY_EDGES_S", "OCCUPANCY_EDGES", "QUANTILES",
           "Tracer", "RequestTrace", "TraceEvent",
           "StepTimeline", "StepRecord",
           "chrome_trace", "write_chrome_trace", "prometheus_text",
           "latency_table"]
