"""paddle_tpu.obs — serving-grade observability.

The layer that answers the operational questions the serving invariants
(compile-once, sync-free decode — paddle_tpu.analysis) cannot: where did a
request spend its time, what are TTFT/TPOT at p50/p99, what did the
engine's step timeline look like when tail latency spiked — and, since
the goodput-attribution layer, WHERE each step's wall time went, whether
the analytic cost models still predict reality, and what the engine was
doing right before it died.

- :mod:`~paddle_tpu.obs.trace` — per-request lifecycle traces
  (:class:`Tracer`, :class:`RequestTrace`): timestamped events from the
  pluggable engine clock, summarized into queue_wait / prefill_time /
  TTFT / TPOT / e2e. O(1) per event, bounded retention.
- :mod:`~paddle_tpu.obs.histogram` — fixed-bucket streaming
  :class:`Histogram` (bounded memory, pre-seeded presence) backing the
  ``serving_ttft_s`` / ``serving_tpot_s`` / ``serving_queue_wait_s`` /
  ``serving_e2e_s`` / ``serving_step_duration_s`` /
  ``serving_batch_occupancy`` percentile gauges, plus
  :class:`HistogramFamily` — label-keyed families
  (``serving_step_phase_s{phase=}``, and the per-tenant latency classes
  the fleet router will reuse).
- :mod:`~paddle_tpu.obs.timeline` — the engine loop's bounded per-step
  ring (:class:`StepTimeline`): phase mix, batch size, page pressure,
  preemptions, per-phase wall-time attribution, host syncs under
  ``debug_checks``.
- :mod:`~paddle_tpu.obs.attribution` — goodput attribution:
  :class:`PhaseAccumulator` (exact per-phase step wall-time split) and
  :class:`RooflineTracker` (live MFU / HBM-bandwidth utilization /
  cost-model drift against the engine's own hlocheck audits, plus the
  kernelcheck predicted-vs-measured speedup A/B).
- :mod:`~paddle_tpu.obs.alerts` — anomaly watchdogs (:class:`Watchdog`):
  edge-triggered rules over host-resident step state — retrace after
  warmup, Pallas fallback, speculative-acceptance collapse, eviction
  thrash, queue stall — each firing a structured :class:`Alert`.
- :mod:`~paddle_tpu.obs.journey` — request-journey records
  (:class:`Journey`, :class:`JourneyBook`): every request's
  enqueue → admit → chunk/decode/verify → preempt/swap → retire hop
  list with engine-step refs, folded off the tracer's event stream and
  exportable as the schema-versioned ``paddle-tpu/journey/v1`` wire
  dict (:func:`validate_journey`) — the trace-export-over-the-wire
  format the multi-host arc consumes.
- :mod:`~paddle_tpu.obs.tenant` — per-tenant SLO classes
  (:class:`TenantSLO`) and the goodput/badput ledger
  (:class:`TenantLedger`): every retirement classified into one of
  seven terminal classes, emitted tokens accrued per class, observe-only
  (weighted admission stays with the fleet router).
- :mod:`~paddle_tpu.obs.recorder` — the black-box flight recorder:
  bounded schema-versioned JSON dumps (v2: + per-tenant roll-ups and a
  journey ring; v1 dumps stay readable) of the step ring + alerts +
  gauges + audit roll-ups, written automatically on engine-fatal paths
  and request failures.
- :mod:`~paddle_tpu.obs.export` — Chrome ``trace_event`` JSON (one track
  per request + the engine loop + counter tracks + alert instants; loads
  in Perfetto) and Prometheus text exposition with labeled families.
- :mod:`~paddle_tpu.obs.fleetscope` — cluster-grain observability:
  cross-replica exchange spans (:class:`FleetScope`, deterministic
  :func:`span_id`, Chrome flow events via :func:`flow_events`),
  fleet-wide scrape merging (:class:`FleetMetrics`, ``replica=``
  labels), and the schema-versioned ``paddle-tpu/fleet-record/v1``
  cluster flight recorder (:func:`validate_fleet_record`) bundling
  per-replica flight records + router state + the exchange-span ring.

``python -m paddle_tpu.obs --flight-record DUMP`` pretty-prints a flight
record (``--prometheus`` / ``--latency-table`` render its gauge and
latency sections); ``--fleet-record DUMP`` pretty-prints a cluster
record (``--span RID`` renders one request's exchange span trees,
``--prometheus`` the merged ``replica=``-labeled exposition); exit 0
clean, 1 alerts/fatal recorded, 2 bad usage.

Imports nothing from ``paddle_tpu.serving`` — serving imports us. Tracing
is on by default in the engine (``ServingConfig(enable_tracing=)``); the
off path costs one attribute check per event site and the on path adds no
host syncs to the decode loop (the SyncTally certification is unchanged).
"""
from .alerts import RULES as ALERT_RULES  # noqa: F401
from .alerts import Alert, Watchdog, WatchdogConfig  # noqa: F401
from .attribution import (DEFAULT_PEAK_FLOPS_PER_S,  # noqa: F401
                          DEFAULT_PEAK_HBM_BYTES_PER_S, PHASES,
                          PhaseAccumulator, RooflineTracker,
                          load_banked_kernel_speedups)
from .export import (chrome_trace, latency_table,  # noqa: F401
                     prometheus_text, write_chrome_trace)
from .fleetscope import (FLEET_RECORD_SCHEMA,  # noqa: F401
                         FleetMetrics, FleetScope, build_fleet_record,
                         dump_fleet_record, flow_events,
                         format_fleet_record, format_span_tree,
                         span_id, span_key, validate_fleet_record)
from .histogram import (LATENCY_EDGES_S, OCCUPANCY_EDGES,  # noqa: F401
                        QUANTILES, Histogram, HistogramFamily,
                        split_labels)
from .journey import (JOURNEY_SCHEMA, Journey, JourneyBook,  # noqa: F401
                      format_journey, validate_journey)
from .recorder import (FLIGHT_RECORD_SCHEMA,  # noqa: F401
                       FLIGHT_RECORD_SCHEMA_V1, build_flight_record,
                       dump_flight_record, format_flight_record,
                       validate_flight_record)
from .tenant import TENANT_CLASSES  # noqa: F401
from .tenant import (TenantLedger, TenantSLO,  # noqa: F401
                     check_tenant_name, tenant_table)
from .timeline import StepRecord, StepTimeline  # noqa: F401
from .trace import RequestTrace, TraceEvent, Tracer  # noqa: F401

__all__ = ["Histogram", "HistogramFamily", "LATENCY_EDGES_S",
           "OCCUPANCY_EDGES", "QUANTILES", "split_labels",
           "Tracer", "RequestTrace", "TraceEvent",
           "StepTimeline", "StepRecord",
           "PHASES", "PhaseAccumulator", "RooflineTracker",
           "DEFAULT_PEAK_FLOPS_PER_S", "DEFAULT_PEAK_HBM_BYTES_PER_S",
           "load_banked_kernel_speedups",
           "Alert", "ALERT_RULES", "Watchdog", "WatchdogConfig",
           "JOURNEY_SCHEMA", "Journey", "JourneyBook",
           "validate_journey", "format_journey",
           "TENANT_CLASSES", "TenantSLO", "TenantLedger",
           "check_tenant_name", "tenant_table",
           "FLIGHT_RECORD_SCHEMA", "FLIGHT_RECORD_SCHEMA_V1",
           "build_flight_record", "dump_flight_record",
           "format_flight_record", "validate_flight_record",
           "chrome_trace", "write_chrome_trace", "prometheus_text",
           "latency_table",
           "FLEET_RECORD_SCHEMA", "FleetScope", "FleetMetrics",
           "span_id", "span_key", "flow_events", "build_fleet_record",
           "dump_fleet_record", "validate_fleet_record",
           "format_fleet_record", "format_span_tree"]
