"""Black-box flight recorder: a bounded JSON dump of what the engine was
doing when something went wrong.

The engine already keeps everything a post-mortem needs — the step
timeline ring, the watchdog alert history, the metrics registry, the
per-program hlocheck audit roll-ups, and the per-request latency
summaries. The flight recorder is the BUNDLER: :func:`build_flight_record`
snapshots those surfaces into one schema-versioned dict (bounded — the
last ``max_steps`` step records, the last ``max_requests`` summaries, the
alert ring is already capped) and :func:`dump_flight_record` writes it as
JSON. The engine dumps automatically on its fatal paths (an exception
escaping the step body, the stuck-engine backstop) and whenever a request
retires FAILED — so every deterministic ``-m faults`` scenario doubles as
a recorder test — and on demand via ``engine.dump_flight_record(path)``.

``python -m paddle_tpu.obs --flight-record dump.json`` pretty-prints a
dump (``--prometheus`` / ``--latency-table`` / ``--tenant-table`` /
``--journey RID`` render its gauge, summary, per-tenant, and journey
sections); :func:`validate_flight_record` is the schema gate both the
CLI and the tests use — it accepts schema ``v2`` (current: adds the
per-tenant goodput roll-ups and a bounded ring of wire journeys) AND
the original ``v1`` (dumps written before the tenant layer existed
stay readable).
"""
from __future__ import annotations

import json
from dataclasses import asdict

from .journey import validate_journey

__all__ = ["FLIGHT_RECORD_SCHEMA", "FLIGHT_RECORD_SCHEMA_V1",
           "MAX_FLIGHT_JOURNEYS", "build_flight_record",
           "dump_flight_record", "validate_flight_record",
           "format_flight_record"]

FLIGHT_RECORD_SCHEMA_V1 = "paddle-tpu/flight-record/v1"
FLIGHT_RECORD_SCHEMA = "paddle-tpu/flight-record/v2"

#: journeys retained per dump — also the bound callers should apply
#: BEFORE serializing (JourneyBook.wire_records(limit=...)), so a
#: failure-path dump is O(kept), not O(every retained journey)
MAX_FLIGHT_JOURNEYS = 64

#: required top-level keys and their types — the schema contract the
#: tests pin and the CLI enforces before pretty-printing; v2 adds the
#: per-tenant roll-ups and the journey ring on top of the v1 set
_SCHEMA_KEYS = (("schema", str), ("reason", str), ("dumped_at", float),
                ("step", int), ("config", dict), ("steps", list),
                ("alerts", list), ("gauges", dict), ("programs", dict),
                ("requests", list))
_SCHEMA_KEYS_V2 = _SCHEMA_KEYS + (("tenants", dict), ("journeys", list))


def build_flight_record(*, reason: str, now: float, step: int,
                        config: dict | None = None, timeline=None,
                        alerts=(), gauges: dict | None = None,
                        programs: dict | None = None, requests=(),
                        tenants: dict | None = None, journeys=(),
                        max_steps: int = 64,
                        max_requests: int = 64,
                        max_journeys: int = MAX_FLIGHT_JOURNEYS) -> dict:
    """Assemble one flight record (schema v2). ``timeline`` is a
    :class:`~paddle_tpu.obs.timeline.StepTimeline` (or None — tracing
    off), ``alerts`` an iterable of :class:`~paddle_tpu.obs.alerts.Alert`
    (or already-dict entries), ``requests`` latency-summary dicts,
    ``tenants`` the :meth:`TenantLedger.rollup` dict, ``journeys`` wire
    journey dicts (the newest ``max_journeys`` are kept)."""
    steps = timeline.records()[-max_steps:] if timeline is not None else []
    return {
        "schema": FLIGHT_RECORD_SCHEMA,
        "reason": str(reason),
        "dumped_at": float(now),
        "step": int(step),
        "config": dict(config or {}),
        "steps": [asdict(r) for r in steps],
        "alerts": [a if isinstance(a, dict) else a.asdict()
                   for a in alerts],
        "gauges": dict(gauges or {}),
        "programs": dict(programs or {}),
        "requests": list(requests)[-max_requests:],
        "tenants": dict(tenants or {}),
        "journeys": list(journeys)[-max_journeys:],
    }


def dump_flight_record(path, record: dict) -> dict:
    """Write the record as JSON; returns it unchanged."""
    with open(path, "w") as f:
        json.dump(record, f, indent=1, default=str)
    return record


def validate_flight_record(record) -> dict:
    """Schema gate: raises ValueError naming the first violation; returns
    the record for chaining."""
    if not isinstance(record, dict):
        raise ValueError(f"flight record must be a dict, got "
                         f"{type(record).__name__}")
    schema = record.get("schema")
    if schema == FLIGHT_RECORD_SCHEMA:
        keys = _SCHEMA_KEYS_V2
    elif schema == FLIGHT_RECORD_SCHEMA_V1:
        keys = _SCHEMA_KEYS  # back-compat: pre-tenant dumps stay readable
    else:
        raise ValueError(
            f"unknown flight-record schema {schema!r} "
            f"(expected {FLIGHT_RECORD_SCHEMA!r} or "
            f"{FLIGHT_RECORD_SCHEMA_V1!r})")
    for key, typ in keys:
        if key not in record:
            raise ValueError(f"flight record missing key {key!r}")
        if typ is float and isinstance(record[key], int):
            continue  # JSON round-trips integral floats as ints
        if not isinstance(record[key], typ):
            raise ValueError(
                f"flight record key {key!r} must be {typ.__name__}, got "
                f"{type(record[key]).__name__}")
    for rec in record["steps"]:
        for field in ("step", "t_start", "t_end"):
            if field not in rec:
                raise ValueError(
                    f"flight-record step entry missing {field!r}: {rec}")
    for alert in record["alerts"]:
        for field in ("rule", "step", "message"):
            if field not in alert:
                raise ValueError(
                    f"flight-record alert entry missing {field!r}: {alert}")
    for journey in record.get("journeys", ()):
        validate_journey(journey)  # each ring entry is itself schema-gated
    return record


def format_flight_record(record: dict) -> str:
    """Human-readable rendering of a (validated) dump — the CLI's default
    view: header, alert table, the newest step records, and the nonzero
    headline gauges."""
    lines = [f"flight record  schema={record['schema']}",
             f"reason: {record['reason']}",
             f"dumped at t={record['dumped_at']:.6f}s, engine step "
             f"{record['step']}"]
    cfg = record["config"]
    if cfg:
        lines.append("config: " + ", ".join(
            f"{k}={v}" for k, v in sorted(cfg.items())))
    lines.append(f"\nalerts ({len(record['alerts'])}):")
    for a in record["alerts"]:
        lines.append(f"  step {a['step']:>5}  {a['rule']:<26} "
                     f"{a['message']}")
    if not record["alerts"]:
        lines.append("  (none)")
    steps = record["steps"]
    lines.append(f"\nsteps (last {len(steps)} retained):")
    for rec in steps[-10:]:
        phases = rec.get("phase_s") or {}
        mix = "+".join(sorted(k for k, v in phases.items() if v)) or "-"
        fatal = (rec.get("extra") or {}).get("fatal")
        dur = rec["t_end"] - rec["t_start"]
        lines.append(
            f"  step {rec['step']:>5}  dur={dur:.6f}s "
            f"batch={rec.get('batch', 0)} "
            f"queue={rec.get('queue_depth', 0)} "
            f"pages={rec.get('pages_in_use', 0)} phases={mix}"
            + (f"  FATAL: {fatal}" if fatal else ""))
    if not steps:
        lines.append("  (tracing was off — no step records)")
    if record["programs"]:
        lines.append("\naudited programs:")
        for label, p in sorted(record["programs"].items()):
            lines.append(f"  {label:<16} flops/step={p.get('flops', 0):.4g}"
                         f"  peak_hbm={p.get('peak_hbm_bytes', 0)}")
    tenants = record.get("tenants") or {}
    if tenants:
        from .tenant import tenant_table

        lines.append(f"\ntenants ({len(tenants)}):")
        lines.append(tenant_table(tenants))
        n_journeys = len(record.get("journeys") or ())
        lines.append(f"journeys retained: {n_journeys} "
                     f"(--journey RID prints one)")
    nonzero = {k: v for k, v in sorted(record["gauges"].items())
               if isinstance(v, (int, float)) and v}
    lines.append(f"\nnonzero gauges ({len(nonzero)}):")
    for k, v in nonzero.items():
        lines.append(f"  {k} = {v}")
    return "\n".join(lines)
