"""``python -m paddle_tpu.obs`` — the observability CLI.

Operates on a flight-record dump (``engine.dump_flight_record(path)`` or
the automatic fatal/failure dumps):

    python -m paddle_tpu.obs --flight-record dump.json
        pretty-print the dump: reason, alert table, newest step records,
        audited programs, nonzero gauges
    python -m paddle_tpu.obs --flight-record dump.json --prometheus
        render the dump's gauge snapshot as Prometheus text exposition
    python -m paddle_tpu.obs --flight-record dump.json --latency-table
        render the dump's per-request latency summaries as the fixed-
        width table
    python -m paddle_tpu.obs --flight-record dump.json --tenant-table
        render the dump's per-tenant roll-ups (goodput %, TTFT/TPOT
        p99, badput breakdown by class) — flight-record v2 dumps only
    python -m paddle_tpu.obs --flight-record dump.json --journey RID
        pretty-print one request's journey out of the dump's bounded
        journey ring (hop table with engine-step refs)
    python -m paddle_tpu.obs --prometheus
        (no dump) text exposition of THIS process's live ``serving_*``
        registry — for embedding in a scrape handler

Cluster-grain dumps (``FleetRouter.dump_fleet_record(path)`` or the
automatic replica-down / chaos-invariant dumps):

    python -m paddle_tpu.obs --fleet-record dump.json
        pretty-print the fleet record: per-replica roll-up table,
        breaker states, router state, exchange-span tally
    python -m paddle_tpu.obs --fleet-record dump.json --span RID
        pretty-print every exchange span tree the dump retained for
        one request (attempt/backoff/breaker children indented)
    python -m paddle_tpu.obs --fleet-record dump.json --prometheus
        merge every bundled replica registry into ONE exposition with
        ``replica=`` labels (the ``FleetMetrics`` dump path)

Exit codes follow the analysis CLI convention: 0 clean, 1 findings (the
dump records alerts or an engine-fatal/failure reason), 2 bad usage or
an unreadable/invalid dump. Also available as ``tools/obs.py``.
"""
from __future__ import annotations

import json
import sys

from .export import latency_table, prometheus_text
from .journey import format_journey
from .recorder import format_flight_record, validate_flight_record
from .tenant import tenant_table


def _counter_types(gauges: dict) -> dict:
    """Type the monotonic names for exposition from the serving
    registry's COUNTER_STATS — the same single source of truth behind
    the live ``ServingMetrics.prometheus()``, so a dump's exposition can
    never type-flap against a live scrape of the same process. (Runtime
    import: the obs LIBRARY modules never import serving — serving
    imports them — but this CLI entry point is never imported by
    serving, so there is no cycle.)"""
    from ..serving.metrics import COUNTER_STATS
    from .histogram import split_labels

    out = {}
    for name in gauges:
        base = split_labels(name)[0]
        if base in COUNTER_STATS:
            out[base] = "counter"
    return out


def _fleet_main(args) -> int:
    """The cluster-grain input: every view over a fleet record."""
    from .fleetscope import (FleetMetrics, format_fleet_record,
                             format_span_tree, validate_fleet_record)

    try:
        with open(args.fleet_record) as fh:
            record = validate_fleet_record(json.load(fh))
    except (OSError, ValueError) as e:
        print(f"cannot read fleet record {args.fleet_record!r}: {e}")
        return 2

    if args.latency_table or args.tenant_table or args.journey is not None:
        print("that view reads a single replica's flight record: pass "
              "--flight-record PATH (a fleet record bundles them under "
              "'replicas')")
        return 2
    if args.span is not None:
        trees = [rec for rec in record["exchanges"]
                 if rec.get("rid") == args.span]
        if not trees:
            retained = sorted({rec.get("rid")
                               for rec in record["exchanges"]
                               if rec.get("rid") is not None})
            print(f"rid {args.span} not in the dump's exchange ring "
                  f"(retained rids: {retained[:16]}"
                  + ("..." if len(retained) > 16 else "") + ")")
            return 2
        print("\n".join(format_span_tree(rec) for rec in trees))
    elif args.prometheus:
        # merge the bundled registries; type the monotonic names off
        # the first replica's gauges (the families are fleet-uniform)
        gauges = (record["replicas"][0].get("gauges", {})
                  if record["replicas"] else {})
        print(FleetMetrics.from_fleet_record(
            record, types=_counter_types(gauges)).prometheus(), end="")
    else:
        print(format_fleet_record(record))
    dirty = bool(record["alerts"]) or record["reason"] != "manual"
    return 1 if dirty else 0


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.obs",
        description="Flight-record reader + Prometheus exposition "
                    "(0 clean, 1 alerts/fatal recorded, 2 bad usage).")
    parser.add_argument("--flight-record", metavar="PATH", default=None,
                        help="flight-record JSON dump to read")
    parser.add_argument("--fleet-record", metavar="PATH", default=None,
                        help="cluster fleet-record JSON dump to read "
                             "(paddle-tpu/fleet-record/v1)")
    view = parser.add_mutually_exclusive_group()
    view.add_argument("--prometheus", action="store_true",
                      help="render the dump's gauges (or, with no dump, "
                           "this process's live serving_* registry) as "
                           "Prometheus text")
    view.add_argument("--latency-table", action="store_true",
                      help="render the dump's per-request latency "
                           "summaries")
    view.add_argument("--tenant-table", action="store_true",
                      help="render the dump's per-tenant goodput/SLO "
                           "roll-ups (flight-record v2)")
    view.add_argument("--journey", metavar="RID", type=int, default=None,
                      help="pretty-print one request's journey out of "
                           "the dump's journey ring")
    view.add_argument("--span", metavar="RID", type=int, default=None,
                      help="pretty-print one request's exchange span "
                           "trees out of a fleet record's ring")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code == 0 else 2

    if args.fleet_record is not None:
        if args.flight_record is not None:
            print("--flight-record and --fleet-record are different "
                  "inputs: pass one")
            return 2
        return _fleet_main(args)
    if args.span is not None:
        print("--span reads a fleet record's exchange ring: pass "
              "--fleet-record PATH")
        return 2

    if args.flight_record is None:
        if args.prometheus:
            from ..utils import monitor

            stats = monitor.stats_with_prefix("serving_")
            print(prometheus_text(stats, types=_counter_types(stats)),
                  end="")
            return 0
        parser.print_usage()
        print("a view needs input: pass --flight-record PATH "
              "(--prometheus alone reads the live registry)")
        return 2

    try:
        with open(args.flight_record) as fh:
            record = validate_flight_record(json.load(fh))
    except (OSError, ValueError) as e:
        print(f"cannot read flight record {args.flight_record!r}: {e}")
        return 2

    if args.prometheus:
        print(prometheus_text(record["gauges"],
                              types=_counter_types(record["gauges"])),
              end="")
    elif args.latency_table:
        print(latency_table(record["requests"]))
    elif args.tenant_table:
        tenants = record.get("tenants")
        if tenants is None:
            print(f"dump {args.flight_record!r} has no tenant section "
                  f"(flight-record v1, pre-tenant)")
            return 2
        print(tenant_table(tenants))
    elif args.journey is not None:
        ring = record.get("journeys")
        if ring is None:  # v1 predates journeys — don't claim eviction
            print(f"dump {args.flight_record!r} has no journey ring "
                  f"(flight-record v1, pre-tenant)")
            return 2
        journeys = {j["rid"]: j for j in ring}
        if args.journey not in journeys:
            retained = sorted(journeys)
            print(f"rid {args.journey} not in the dump's journey ring "
                  f"(retained rids: {retained[:16]}"
                  + ("..." if len(retained) > 16 else "") + ")")
            return 2
        print(format_journey(journeys[args.journey]))
    else:
        print(format_flight_record(record))
    # findings contract: a dump that recorded alerts, or was written by a
    # fatal/failure path, is a finding — scriptable triage
    dirty = bool(record["alerts"]) or record["reason"] != "manual"
    return 1 if dirty else 0


if __name__ == "__main__":
    sys.exit(main())
