"""Per-request lifecycle tracing for the serving engine.

A :class:`RequestTrace` is an append-only list of ``(event, timestamp,
args)`` triples covering one request's whole life — enqueued, admitted,
prefill_start/prefill_end (with a ``prefill_chunk`` per chunk in between
under chunked prefill — TTFT stays anchored to ``first_token``, which
only the FINAL chunk emits), first_token, periodic decode_mark, preempted /
swap_out / swap_in / resumed, host-tier ``spill`` / ``restore`` (prefix
pages this admission pushed to or pulled from the host cache tier), and a
terminal ``retired`` carrying the final state
(finished/cancelled/expired/failed/shed). Timestamps come from the
ENGINE clock (``ServingConfig(clock=)`` + fault skew), never from the wall
clock directly: every trace behavior is testable sleep-free with a virtual
clock, and the ``slow_step`` fault's skew shows up in traces exactly like
it does in deadlines.

The :class:`Tracer` is the engine-owned store (rid -> trace). Contracts:

- **O(1) per event**: an event is one dict lookup + one list append; no
  summarization happens on the hot path. Summaries (queue_wait, prefill
  time, TTFT, TPOT, e2e) are computed on demand from the raw events.
- **Bounded memory**: retention returns to ``capacity`` whenever traces
  are available to evict — oldest TERMINAL first; live requests always
  keep their traces (truncating an in-flight trace would fabricate a
  lifecycle), so an all-live burst may transiently exceed the bound and
  is reclaimed as those requests retire.
- **Preemption-resumable**: a preempted request's trace keeps
  accumulating through re-admission — a recompute victim shows a second
  ``prefill_start``, a swap victim shows ``swap_in``/``resumed`` — so the
  summary's TTFT stays anchored to the FIRST token the client ever saw.
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass

__all__ = ["TraceEvent", "RequestTrace", "Tracer"]

# terminal event name; its ``state`` arg is the request's final state
RETIRED = "retired"


@dataclass(frozen=True)
class TraceEvent:
    name: str
    t: float  # engine-clock seconds
    args: dict | None = None

    def arg(self, key, default=None):
        return self.args.get(key, default) if self.args else default


class RequestTrace:
    """One request's lifecycle: ordered events + derived latency summary."""

    __slots__ = ("rid", "events", "state")

    def __init__(self, rid: int):
        self.rid = rid
        self.events: list[TraceEvent] = []
        self.state: str | None = None  # terminal state once retired

    def add(self, name: str, t: float, args: dict | None = None) -> None:
        self.events.append(TraceEvent(name, t, args))
        if name == RETIRED:
            self.state = args.get("state") if args else None

    @property
    def terminal(self) -> bool:
        return self.state is not None

    def first(self, name: str) -> TraceEvent | None:
        return next((e for e in self.events if e.name == name), None)

    def last(self, name: str) -> TraceEvent | None:
        return next((e for e in reversed(self.events) if e.name == name),
                    None)

    def count(self, name: str) -> int:
        return sum(1 for e in self.events if e.name == name)

    def summary(self) -> dict:
        """The latency decomposition (seconds; None when the lifecycle
        never reached the relevant milestone — e.g. TTFT of a request
        cancelled while waiting):

        - ``queue_wait``: enqueued -> FIRST admission,
        - ``prefill_time``: first prefill_start -> first prefill_end,
        - ``ttft``: enqueued -> first_token (time to first token),
        - ``tpot``: (last token - first token) / (tokens - 1) — mean
          client-observed time per output token (preemption stalls
          included, as the client experiences them); FINISHED requests
          with >= 2 tokens only — a cancelled/expired retirement can
          happen arbitrarily long after the last token was produced, so
          its retirement time says nothing about decode speed,
        - ``e2e``: enqueued -> retired,

        plus ``state``, ``tokens`` (generated count at retirement),
        ``preemptions``, ``cached_tokens`` (prefix-cache hit width), and
        ``prefill_chunks`` (chunked-prefill chunk count; 0 unchunked).
        """
        enq = self.first("enqueued")
        adm = self.first("admitted")
        ps, pe = self.first("prefill_start"), self.first("prefill_end")
        ft = self.first("first_token")
        ret = self.last(RETIRED)
        tokens = ret.arg("tokens", 0) if ret else 0

        def dt(a, b):
            return b.t - a.t if a is not None and b is not None else None

        tpot = None
        if ft is not None and ret is not None and tokens and tokens > 1 \
                and ret.arg("state") == "finished":
            # the final token lands in the same step boundary that retires
            # a FINISHED request, so retirement time IS last-token time;
            # any other terminal state retires at some later sweep and
            # would smear queue/swap wait into the per-token figure
            tpot = (ret.t - ft.t) / (tokens - 1)
        return {
            "rid": self.rid,
            "state": self.state,
            "tokens": tokens,
            "queue_wait": dt(enq, adm),
            "prefill_time": dt(ps, pe),
            "ttft": dt(enq, ft),
            "tpot": tpot,
            "e2e": dt(enq, ret),
            "preemptions": self.count("preempted"),
            "cached_tokens": ps.arg("cached", 0) if ps else 0,
            "prefill_chunks": self.count("prefill_chunk"),
        }

    def __repr__(self) -> str:
        names = [e.name for e in self.events]
        return f"RequestTrace(rid={self.rid}, state={self.state}, {names})"


class Tracer:
    """Engine-owned trace store. Every mutation is O(1); eviction only
    runs at trace creation and only removes terminal traces."""

    def __init__(self, clock, capacity: int = 2048, mark_every: int = 32):
        if capacity < 1:
            raise ValueError(f"capacity {capacity} < 1")
        if mark_every < 1:
            raise ValueError(f"mark_every {mark_every} < 1")
        self._clock = clock
        self.capacity = capacity
        self.mark_every = mark_every  # decode_mark cadence, in tokens
        self._traces: OrderedDict[int, RequestTrace] = OrderedDict()
        self.evicted = 0
        # optional event-stream tap: a callable(rid, name, t, args) every
        # event ALSO flows through — the journey book subscribes here, so
        # journeys fold over the exact stream the traces record with zero
        # new instrumentation sites (and one attribute check when unset)
        self.journal = None

    def begin(self, rid: int) -> RequestTrace:
        """Create the trace for a new request and stamp ``enqueued``.
        Evicts oldest-first TERMINAL traces until the store is back under
        ``capacity`` — an all-live burst may grow past the bound rather
        than corrupt an in-flight lifecycle, but the store returns to
        ``capacity`` as soon as enough of those traces retire."""
        if len(self._traces) >= self.capacity:
            for key in [k for k, t in self._traces.items() if t.terminal]:
                if len(self._traces) < self.capacity:
                    break
                del self._traces[key]
                self.evicted += 1
        trace = RequestTrace(rid)
        self._traces[rid] = trace
        t = self._clock()
        trace.add("enqueued", t)
        j = self.journal
        if j is not None:
            j(rid, "enqueued", t, None)
        return trace

    def event(self, rid: int, name: str, **args) -> None:
        """Append one timestamped event — a dict lookup and a list append.
        Unknown rids are ignored (the trace was evicted under memory
        pressure; dropping a late event beats unbounded retention)."""
        trace = self._traces.get(rid)
        if trace is not None:
            t = self._clock()
            trace.add(name, t, args or None)
            j = self.journal
            if j is not None:
                j(rid, name, t, args)

    def get(self, rid: int) -> RequestTrace | None:
        return self._traces.get(rid)

    def traces(self) -> list[RequestTrace]:
        """Every retained trace, oldest first."""
        return list(self._traces.values())

    def summaries(self) -> list[dict]:
        return [t.summary() for t in self._traces.values()]

    def __len__(self) -> int:
        return len(self._traces)
