"""INT8 execution + calibration algorithms.

Reference analog: `python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py` (algo dispatch: abs_max / KL / hist / mse /
avg, ~line 360) and `quantization_pass.py` (QuantizationFreezePass — replace
fake-quant pairs with real int8 weights + dequant on the output).

TPU-native design: XLA supports int8 x int8 -> int32 dots/convs on the MXU
natively (`preferred_element_type=int32`), so "freezing" a quantized model
here means swapping Linear/Conv2D for Int8Linear/Int8Conv2D — weights stored
as int8 codebooks (4x smaller), activations quantized on entry with the
calibrated scale, accumulation in int32, one fused rescale at the exit. No
separate quant program pass is needed: the swap IS the pass, and XLA fuses
the quant/rescale arithmetic into the surrounding computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = [
    "Int8Linear", "Int8Conv2D", "convert_to_int8", "load_quantized_model",
    "compute_kl_scale", "compute_mse_scale", "compute_hist_scale",
    "HistogramObserver",
]

# ------------------------------------------------------------- calibration
class HistogramObserver:
    """Accumulates |x| histograms across calibration batches with dynamic
    range growth (rebinning), the structure the KL/hist/mse algorithms need.
    Reference: PostTrainingQuantization._sample_histogram."""

    def __init__(self, bins=2048):
        self.bins = bins
        self.hist = np.zeros(bins, np.float64)
        self.amax = 0.0
        self.batch_maxes = []

    def observe(self, x):
        a = np.abs(np.asarray(x)).ravel()
        m = float(a.max()) if a.size else 0.0
        self.batch_maxes.append(m)
        if m <= 0:
            return
        if m > self.amax:
            if self.amax > 0:
                # stretch the old histogram onto the new range
                old_edges = np.linspace(0, self.amax, self.bins + 1)
                centers = (old_edges[:-1] + old_edges[1:]) / 2
                new_hist, _ = np.histogram(
                    centers, bins=self.bins, range=(0, m), weights=self.hist)
                self.hist = new_hist
            self.amax = m
        h, _ = np.histogram(a, bins=self.bins, range=(0, self.amax))
        self.hist += h


def _kl_divergence(p, q):
    p = p / max(p.sum(), 1e-12)
    q = q / max(q.sum(), 1e-12)
    mask = p > 0
    return float(np.sum(p[mask] * np.log(p[mask] / np.maximum(q[mask], 1e-12))))


def compute_kl_scale(hist, amax, num_quant_bins=128):
    """TensorRT-style KL threshold selection (the reference's algo='KL',
    post_training_quantization.py cal_kl_threshold): pick the clip point
    whose 128-bin quantized distribution diverges least from the clipped
    reference distribution."""
    bins = len(hist)
    if amax <= 0 or hist.sum() == 0:
        return max(amax, 1e-8)
    # drop the zero bin: exact zeros (the post-relu spike) quantize exactly
    # at ANY scale, and their mass otherwise drags the optimal clip toward
    # zero (the TensorRT KL convention)
    hist = hist.copy()
    hist[0] = 0
    if hist.sum() == 0:
        return max(amax, 1e-8)
    bin_width = amax / bins
    best_i, best_kl = bins, np.inf
    # descending, with strict improvement: on near-uniform distributions
    # every clip point ties at KL~0, and the tie must go to the LARGEST
    # range (no clip), not the smallest (which would clip 90%+ of the mass)
    for i in range(bins, num_quant_bins - 1, -8):
        p = hist[:i].astype(np.float64).copy()
        p[i - 1] += hist[i:].sum()  # clip outliers into the last bin
        # quantize the first i bins down to num_quant_bins levels
        factor = i / num_quant_bins
        idx = (np.arange(i) / factor).astype(np.int64)
        q_small = np.bincount(idx, weights=hist[:i], minlength=num_quant_bins)
        # expand back, spreading each level over its source bins (only where
        # the source had mass — empty bins stay empty, as in the reference)
        counts = np.bincount(idx, weights=(hist[:i] > 0).astype(np.float64),
                             minlength=num_quant_bins)
        q = np.where(hist[:i] > 0,
                     q_small[idx] / np.maximum(counts[idx], 1), 0.0)
        kl = _kl_divergence(p, q)
        if kl < best_kl:
            best_kl, best_i = kl, i
    return (best_i + 0.5) * bin_width


def compute_mse_scale(hist, amax, bits=8):
    """Clip threshold minimizing expected squared quantization error over the
    histogram (reference algo='mse')."""
    bins = len(hist)
    if amax <= 0 or hist.sum() == 0:
        return max(amax, 1e-8)
    bin_width = amax / bins
    centers = (np.arange(bins) + 0.5) * bin_width
    qmax = 2.0 ** (bits - 1) - 1
    best_t, best_err = amax, np.inf
    for i in range(bins // 8, bins + 1, 8):
        t = i * bin_width
        step = t / qmax
        clipped = np.minimum(centers, t)
        deq = np.round(clipped / step) * step
        err = float(np.sum(hist * (centers - deq) ** 2))
        if err < best_err:
            best_err, best_t = err, t
    return best_t


def compute_hist_scale(hist, amax, percent=0.99999):
    """Percentile clip (reference algo='hist', hist_percent)."""
    if amax <= 0 or hist.sum() == 0:
        return max(amax, 1e-8)
    cdf = np.cumsum(hist) / hist.sum()
    i = int(np.searchsorted(cdf, percent)) + 1
    return i * (amax / len(hist))


# --------------------------------------------------------------- int8 layers
class Int8Linear(Layer):
    """Linear with an int8 weight codebook and int8 MXU execution:
    x -> int8 (calibrated scale), dot int8xint8 -> int32, one rescale out."""

    def __init__(self, w_int8, w_scale, act_scale, bias=None,
                 weight_bits=8, activation_bits=8):
        super().__init__()
        self.register_buffer("w_int8", Tensor(jnp.asarray(w_int8, jnp.int8)))
        # dequant factor per output channel: w_scale [1, out] / qmax
        self._w_scale = np.asarray(w_scale, np.float32).reshape(1, -1)
        self._act_scale = float(act_scale)
        self._w_qmax = float(2 ** (weight_bits - 1) - 1)
        self._a_qmax = float(2 ** (activation_bits - 1) - 1)
        self.bias = bias

    def forward(self, x):
        w = self.w_int8
        w_scale, act_scale = self._w_scale, self._act_scale
        w_qmax, a_qmax = self._w_qmax, self._a_qmax
        bias = self.bias

        def f(xv, wv, *b):
            xq = jnp.clip(jnp.round(xv / act_scale * a_qmax), -a_qmax, a_qmax
                          ).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, wv, (((xv.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (
                jnp.asarray(w_scale) * act_scale / (w_qmax * a_qmax))
            if b:
                out = out + b[0]
            return out.astype(xv.dtype)

        args = [x, w] + ([self.bias] if bias is not None else [])
        return primitive_call(f, *args, name="int8_linear",
                              attrs={"act_scale": act_scale})


class Int8Conv2D(Layer):
    """Conv2D executing in int8 (NCHW): int8 feature map x int8 kernel ->
    int32 accumulate, per-output-channel rescale at the exit."""

    def __init__(self, w_int8, w_scale, act_scale, bias=None, stride=(1, 1),
                 padding=0, dilation=(1, 1), groups=1, data_format="NCHW",
                 weight_bits=8, activation_bits=8):
        super().__init__()
        if data_format != "NCHW":
            raise NotImplementedError(
                "Int8Conv2D supports NCHW only (the reference int8 pass is "
                "also NCHW); convert the model or keep this layer float")
        from ..nn.functional import _conv_padding, _pair

        self.register_buffer("w_int8", Tensor(jnp.asarray(w_int8, jnp.int8)))
        self._w_scale = np.asarray(w_scale, np.float32).reshape(1, -1, 1, 1)
        self._act_scale = float(act_scale)
        self.bias = bias
        self._stride = _pair(stride)
        self._dilation = _pair(dilation)
        self._pad = _conv_padding(padding, None, self._dilation, 2)
        self._groups = groups
        self._w_qmax = float(2 ** (weight_bits - 1) - 1)
        self._a_qmax = float(2 ** (activation_bits - 1) - 1)

    def forward(self, x):
        w = self.w_int8
        w_scale, act_scale = self._w_scale, self._act_scale
        stride, pad, dil, groups = (self._stride, self._pad, self._dilation,
                                    self._groups)
        w_qmax, a_qmax = self._w_qmax, self._a_qmax
        bias = self.bias

        def f(xv, wv, *b):
            xq = jnp.clip(jnp.round(xv / act_scale * a_qmax), -a_qmax, a_qmax
                          ).astype(jnp.int8)
            acc = jax.lax.conv_general_dilated(
                xq, wv, window_strides=stride, padding=pad,
                rhs_dilation=dil,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=groups,
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (
                jnp.asarray(w_scale) * act_scale / (w_qmax * a_qmax))
            if b:
                out = out + b[0].reshape(1, -1, 1, 1)
            return out.astype(xv.dtype)

        args = [x, w] + ([self.bias] if bias is not None else [])
        return primitive_call(f, *args, name="int8_conv2d",
                              attrs={"act_scale": act_scale})


# ----------------------------------------------------------------- converter
def convert_to_int8(model: Layer, scales: dict, weight_bits=8,
                    activation_bits=8) -> int:
    """Swap each calibrated QuantedLinear/QuantedConv2D for its int8
    executing twin, consuming the PTQ scales dict ({sublayer name ->
    {weight_int8, weight_scale, act_scale}}). Returns the number of layers
    converted. The reference analog is QuantizationFreezePass: fake-quant
    pairs become real int8 weights + dequant."""
    from . import QuantedConv2D, QuantedLinear

    n = 0
    for parent_name, parent in [("", model)] + list(model.named_sublayers()):
        for name, sub in list(parent._sub_layers.items()):
            full = f"{parent_name}.{name}" if parent_name else name
            if full not in scales:
                continue
            rec = scales[full]
            if isinstance(sub, QuantedLinear):
                parent._sub_layers[name] = Int8Linear(
                    rec["weight_int8"], rec["weight_scale"],
                    rec["act_scale"], bias=sub.bias,
                    weight_bits=weight_bits, activation_bits=activation_bits)
                n += 1
            elif isinstance(sub, QuantedConv2D):
                lay = sub._inner
                parent._sub_layers[name] = Int8Conv2D(
                    rec["weight_int8"], rec["weight_scale"],
                    rec["act_scale"], bias=sub.bias,
                    stride=lay._stride, padding=lay._padding,
                    dilation=lay._dilation, groups=lay._groups,
                    data_format=lay._data_format,
                    weight_bits=weight_bits, activation_bits=activation_bits)
                n += 1
    return n


def load_quantized_model(model: Layer, quant_path: str) -> int:
    """Consume a `.quant` sidecar written by
    PostTrainingQuantization.save_quantized_model: quantize `model` (a fresh
    float architecture), then freeze it to int8 with the saved codebooks and
    scales. Returns the number of int8 layers installed."""
    import pickle

    from . import ImperativeQuantAware

    path = quant_path if quant_path.endswith(".quant") else quant_path + ".quant"
    with open(path, "rb") as f:
        payload = pickle.load(f)
    wb = payload.get("weight_bits", 8)
    ab = payload.get("activation_bits", 8)
    ImperativeQuantAware(
        payload.get("quantizable_op_type", ("Linear", "Conv2D")),
        weight_bits=wb, activation_bits=ab).quantize(model)
    state = payload.get("state_dict")
    if state:
        # restore the calibration-time float state (biases, unquantized
        # layers) — a fresh architecture's random init must not leak into
        # the deploy model. Quantized-layer weights are absent (their int8
        # codebooks in `scales` replace them at convert time).
        model.set_state_dict({k: Tensor(np.asarray(v))
                              for k, v in state.items()})
    return convert_to_int8(model, payload["scales"], weight_bits=wb,
                           activation_bits=ab)
