"""paddle quantization — QAT (fake-quant training) and PTQ (post-training).

Reference analog: `python/paddle/fluid/contrib/slim/quantization/` —
`ImperativeQuantAware` (imperative_qat) swaps Linear/Conv layers for quantized
wrappers with fake-quant ops (`fake_quantize_dequantize_moving_average_abs_max`
etc.), `PostTrainingQuantization` calibrates activation scales from sample data
and rewrites the inference program.

TPU-native design: fake-quant is a pure-jax function with a straight-through
estimator (`x + stop_gradient(q(x) - x)`), so the QAT forward/backward fuses
into the same single XLA computation as the float model — no custom kernels
needed. PTQ runs the captured program over calibration batches to collect
abs-max scales, then bakes (int8 weight, scale) pairs into the exported model.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer import Layer

__all__ = [
    "fake_quant", "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
    "QuantedLinear", "QuantedConv2D", "ImperativeQuantAware",
    "PostTrainingQuantization", "quant_post_static", "weight_quantize",
    "weight_dequantize",
    "Int8Linear", "Int8Conv2D", "convert_to_int8", "load_quantized_model",
]


# ------------------------------------------------------------------ primitives
def _quant_dequant(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fake_quant_raw(xv, sv, bits=8):
    # straight-through estimator: forward = quant-dequant, gradient = identity
    return xv + jax.lax.stop_gradient(_quant_dequant(xv, sv, bits) - xv)


def fake_quant(x, scale, bits=8):
    """Quantize-dequantize with straight-through gradient (reference op:
    fake_quantize_dequantize_abs_max, operators/fake_quantize_op.cc)."""
    sv = scale if isinstance(scale, Tensor) else jnp.asarray(scale)
    return primitive_call(_fake_quant_raw, x, sv, bits=bits,
                          name="fake_quantize_dequantize_abs_max")


class FakeQuantAbsMax(Layer):
    """Per-tensor (or per-channel for weights) abs-max fake quantizer."""

    def __init__(self, bits=8, channel_axis=None):
        super().__init__()
        self.bits = bits
        self.channel_axis = channel_axis

    def forward(self, x):
        bits, channel_axis = self.bits, self.channel_axis

        def raw(xv):
            if channel_axis is None:
                s = jnp.max(jnp.abs(xv))
            else:
                axes = tuple(i for i in range(xv.ndim) if i != channel_axis)
                shape = [1] * xv.ndim
                shape[channel_axis] = -1
                s = jnp.max(jnp.abs(xv), axis=axes).reshape(shape)
            return _fake_quant_raw(xv, jax.lax.stop_gradient(s), bits)

        return primitive_call(raw, x, name="fake_quantize_abs_max")


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation quantizer with a running scale: EMA (`algo='ema'`, QAT
    default; reference: fake_quantize_dequantize_moving_average_abs_max) or
    running max over all observed batches (`algo='max'`, the PTQ 'abs_max'
    calibration rule)."""

    def __init__(self, bits=8, moving_rate=0.9, algo="ema"):
        super().__init__()
        self.bits = bits
        self.moving_rate = moving_rate
        self.algo = algo
        # calibration override: None -> follow self.training (QAT); True/False
        # -> forced by PTQ so calibration can run with eval() semantics
        # (dropout off, BN frozen) while the observer still updates
        self._observing = None
        self.scale = self.create_buffer("scale", np.zeros((), np.float32))

    def create_buffer(self, name, value):
        t = Tensor(np.asarray(value), stop_gradient=True)
        self._buffers[name] = t
        return t

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else x
        observing = self.training if self._observing is None else self._observing
        # observer update only on concrete values: under jit tracing the
        # update would leak a tracer into the persistent buffer
        if observing and not isinstance(xv, jax.core.Tracer):
            if getattr(self, "_hist_observer", None) is not None:
                self._hist_observer.observe(xv)
            cur = jax.lax.stop_gradient(jnp.max(jnp.abs(xv))).astype(jnp.float32)
            prev = self.scale._value
            if self.algo == "max":
                self.scale._value = jnp.maximum(prev, cur)
            else:
                r = self.moving_rate
                self.scale._value = jnp.where(prev > 0, r * prev + (1 - r) * cur,
                                              cur)
        return primitive_call(_fake_quant_raw, x, self.scale._value,
                              bits=self.bits,
                              name="fake_quantize_dequantize_moving_average_abs_max")


# ------------------------------------------------------------ quantized layers
class QuantedLinear(Layer):
    """reference: slim/quantization/imperative/qat.py QuantizedLinear."""

    def __init__(self, layer, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 act_algo="ema"):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self._w_quant = FakeQuantAbsMax(weight_bits, channel_axis=1)
        self._a_quant = FakeQuantMovingAverageAbsMax(activation_bits, moving_rate,
                                                     algo=act_algo)

    def forward(self, x):
        x = self._a_quant(x)
        w = self._w_quant(self.weight)
        return F.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 act_algo="ema"):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self._inner = layer
        self._w_quant = FakeQuantAbsMax(weight_bits, channel_axis=0)
        self._a_quant = FakeQuantMovingAverageAbsMax(activation_bits, moving_rate,
                                                     algo=act_algo)

    def forward(self, x):
        x = self._a_quant(x)
        w = self._w_quant(self.weight)
        lay = self._inner
        return F.conv2d(x, w, self.bias, lay._stride, lay._padding,
                        lay._dilation, lay._groups, lay._data_format)


_QUANT_WRAPPERS = {"Linear": QuantedLinear, "Conv2D": QuantedConv2D}


class ImperativeQuantAware:
    """reference: slim/quantization/imperative/qat.py:80 ImperativeQuantAware."""

    def __init__(self, quantizable_layer_type=("Linear", "Conv2D"),
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 act_algo="ema"):
        self.types = tuple(quantizable_layer_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self.act_algo = act_algo

    def quantize(self, model: Layer):
        """Swap quantizable sublayers in place (returns model)."""
        for parent in [model] + [s for _, s in model.named_sublayers()]:
            for name, sub in list(parent._sub_layers.items()):
                cls = type(sub).__name__
                if cls in self.types and cls in _QUANT_WRAPPERS:
                    parent._sub_layers[name] = _QUANT_WRAPPERS[cls](
                        sub, self.weight_bits, self.activation_bits,
                        self.moving_rate, act_algo=self.act_algo)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit

        model.eval()
        jit.save(model, path, input_spec=input_spec)


# ---------------------------------------------------------------------- PTQ
def weight_quantize(w, bits=8, channel_axis=None):
    """float weight -> (int8 array, float scale) per tensor/channel."""
    wv = np.asarray(w.numpy() if isinstance(w, Tensor) else w)
    qmax = float(2 ** (bits - 1) - 1)
    if channel_axis is None:
        scale = np.maximum(np.abs(wv).max(), 1e-8)
    else:
        axes = tuple(i for i in range(wv.ndim) if i != channel_axis)
        shape = [1] * wv.ndim
        shape[channel_axis] = -1
        scale = np.maximum(np.abs(wv).max(axis=axes).reshape(shape), 1e-8)
    q = np.clip(np.round(wv / scale * qmax), -qmax, qmax).astype(np.int8)
    return q, scale


def weight_dequantize(q, scale, bits=8, dtype="float32"):
    qmax = float(2 ** (bits - 1) - 1)
    return (np.asarray(q, dtype) * np.asarray(scale, dtype) / qmax).astype(dtype)


class PostTrainingQuantization:
    """reference: slim/quantization/post_training_quantization.py.

    Calibrates activation abs-max scales by running the model over sample
    batches, quantizes weights per-channel to int8, and exports a model whose
    forward fake-quantizes activations with the calibrated (frozen) scales —
    numerically identical to an int8 deploy with dequant-at-use.
    """

    def __init__(self, model: Layer = None, data_loader=None, batch_nums=None,
                 algo="abs_max", weight_bits=8, activation_bits=8,
                 quantizable_op_type=("Linear", "Conv2D"), executor=None,
                 sample_generator=None):
        self.model = model
        self.data_loader = data_loader
        self.batch_nums = batch_nums
        self.algo = algo
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.types = tuple(quantizable_op_type)
        self.scales = {}

    def quantize(self):
        model = self.model
        # abs_max/KL/hist/mse/avg all track the running max as the base
        # range; the histogram algos then REFINE the clip point from the
        # collected distribution (reference algo dispatch:
        # post_training_quantization.py ~line 360)
        hist_algos = ("KL", "kl", "hist", "mse", "avg")
        qat = ImperativeQuantAware(
            self.types, self.weight_bits, self.activation_bits,
            act_algo="ema" if self.algo == "ema" else "max")
        qat.quantize(model)
        if self.algo in hist_algos:
            from .int8 import HistogramObserver

            for _, sub in model.named_sublayers():
                if isinstance(sub, FakeQuantMovingAverageAbsMax):
                    sub._hist_observer = HistogramObserver()
        # calibration runs with INFERENCE semantics (reference PTQ executes the
        # inference program: dropout off, BN running stats frozen) — the
        # observers update via the explicit _observing override, not train()
        from ..core.tape import no_grad

        model.eval()
        observers = [sub for _, sub in model.named_sublayers()
                     if isinstance(sub, FakeQuantMovingAverageAbsMax)]
        for ob in observers:
            ob._observing = True
        try:
            with no_grad():
                for i, batch in enumerate(self.data_loader):
                    if self.batch_nums and i >= self.batch_nums:
                        break
                    xs = batch if isinstance(batch, (list, tuple)) else [batch]
                    model(*[x if isinstance(x, Tensor) else Tensor(np.asarray(x))
                            for x in xs])
        finally:
            for ob in observers:
                ob._observing = None
        # refine activation scales from the collected histograms
        if self.algo in hist_algos:
            from .int8 import (compute_hist_scale, compute_kl_scale,
                               compute_mse_scale)

            for _, sub in model.named_sublayers():
                ob = getattr(sub, "_hist_observer", None)
                if not isinstance(sub, FakeQuantMovingAverageAbsMax) \
                        or ob is None:
                    continue
                if self.algo in ("KL", "kl"):
                    s = compute_kl_scale(ob.hist, ob.amax)
                elif self.algo == "mse":
                    s = compute_mse_scale(ob.hist, ob.amax,
                                          self.activation_bits)
                elif self.algo == "hist":
                    s = compute_hist_scale(ob.hist, ob.amax)
                else:  # avg — mean of per-batch abs maxes
                    s = float(np.mean(ob.batch_maxes)) if ob.batch_maxes \
                        else float(ob.amax)
                sub.scale._value = jnp.asarray(s, jnp.float32)
                sub._hist_observer = None
        # snapshot the weight int8 codebooks + frozen activation scales
        for name, sub in model.named_sublayers():
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                ca = 1 if isinstance(sub, QuantedLinear) else 0
                q, s = weight_quantize(sub.weight, self.weight_bits, ca)
                self.scales[name] = {
                    "weight_int8": q, "weight_scale": s,
                    "act_scale": float(np.asarray(sub._a_quant.scale._value)),
                }
        return self.model

    def convert_to_int8(self):
        """Freeze the calibrated model to int8 execution in place (the
        QuantizationFreezePass analog). Returns the number of layers
        converted; the model's Linear/Conv2D now run int8 MXU dots."""
        from .int8 import convert_to_int8 as _conv

        return _conv(self.model, self.scales, weight_bits=self.weight_bits,
                     activation_bits=self.activation_bits)

    def save_quantized_model(self, save_model_path, input_spec=None):
        import pickle

        from .. import jit

        jit.save(self.model, save_model_path, input_spec=input_spec)
        # the sidecar is self-contained: int8 codebooks + scales + the full
        # float state (biases, scale buffers, any unquantized layers), so
        # load_quantized_model reproduces the deploy model from a FRESH
        # architecture without a separate checkpoint
        quantized_weight_keys = {f"{name}.weight" for name in self.scales}
        state = {k: np.asarray(v.numpy())
                 for k, v in self.model.state_dict().items()
                 if v is not None and k not in quantized_weight_keys}
        with open(save_model_path + ".quant", "wb") as f:
            pickle.dump({"scales": self.scales, "weight_bits": self.weight_bits,
                         "activation_bits": self.activation_bits,
                         "quantizable_op_type": self.types,
                         "state_dict": state}, f, protocol=4)


from .int8 import (  # noqa: E402
    Int8Conv2D, Int8Linear, convert_to_int8, load_quantized_model)


def quant_post_static(executor=None, model_dir=None, quantize_model_path=None,
                      model=None, data_loader=None, batch_nums=10, **kw):
    """Functional wrapper (reference: paddleslim quant_post_static)."""
    ptq = PostTrainingQuantization(model=model, data_loader=data_loader,
                                   batch_nums=batch_nums, **kw)
    ptq.quantize()
    if quantize_model_path:
        ptq.save_quantized_model(quantize_model_path)
    return ptq
