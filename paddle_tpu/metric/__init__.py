"""paddle.metric (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        label = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            label = label.argmax(axis=-1) if label.shape[-1] != 1 else label.squeeze(-1)
        correct = idx == label[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        accs = []
        num = c.reshape(-1, c.shape[-1]).shape[0]
        for k in self.topk:
            ck = c[..., :k].any(axis=-1).sum()
            self.total[self.topk.index(k)] += ck
            self.count[self.topk.index(k)] += num
            accs.append(float(ck) / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        pred_pos = np.rint(preds).astype(bool).reshape(-1)
        lab = labels.astype(bool).reshape(-1)
        self.tp += int((pred_pos & lab).sum())
        self.fp += int((pred_pos & ~lab).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        pred_pos = np.rint(preds).astype(bool).reshape(-1)
        lab = labels.astype(bool).reshape(-1)
        self.tp += int((pred_pos & lab).sum())
        self.fn += int((~pred_pos & lab).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = labels.reshape(-1)
        idx = np.minimum(
            (preds * self.num_thresholds).astype(np.int64), self.num_thresholds - 1
        )
        for i, l in zip(idx, labels):
            if l:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, dtype=np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate over thresholds from high to low
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp

    from ..core.dispatch import primitive_call

    def f(pred, lab):
        topk_idx = jnp.argsort(-pred, axis=-1)[..., :k]
        l = lab.reshape(-1, 1)
        return jnp.mean(jnp.any(topk_idx == l, axis=-1).astype(jnp.float32))

    return primitive_call(f, input if isinstance(input, Tensor) else Tensor(input),
                          (label if isinstance(label, Tensor) else Tensor(label)).detach())
