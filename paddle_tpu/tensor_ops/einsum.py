"""einsum (reference: python/paddle/tensor/einsum.py) — direct XLA lowering."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor


def einsum(equation, *operands):
    ts = [o if isinstance(o, Tensor) else Tensor(o) for o in operands]
    return primitive_call(lambda *arrs: jnp.einsum(equation, *arrs), *ts, name="einsum")
