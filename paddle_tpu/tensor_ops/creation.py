"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.dtype import get_default_dtype, to_jax_dtype
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty",
    "empty_like",
    "arange",
    "linspace",
    "eye",
    "tril",
    "triu",
    "diag",
    "diagflat",
    "meshgrid",
    "assign",
    "clone",
    "numel",
    "one_hot",
]


def _dt(dtype, default=None):
    if dtype is None:
        dtype = default or get_default_dtype()
    return to_jax_dtype(dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def zeros_like(x, dtype=None, name=None):
    return primitive_call(lambda a: jnp.zeros_like(a, dtype=to_jax_dtype(dtype)), x, name="zeros_like")


def ones_like(x, dtype=None, name=None):
    return primitive_call(lambda a: jnp.ones_like(a, dtype=to_jax_dtype(dtype)), x, name="ones_like")


def full_like(x, fill_value, dtype=None, name=None):
    return primitive_call(
        lambda a: jnp.full_like(a, fill_value, dtype=to_jax_dtype(dtype)), x, name="full_like"
    )


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = _scalar(start), _scalar(end), _scalar(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            "int64"
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else get_default_dtype()
        )
    return Tensor(jnp.arange(start, end, step, dtype=to_jax_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def tril(x, diagonal=0, name=None):
    return primitive_call(lambda a: jnp.tril(a, diagonal), x, name="tril")


def triu(x, diagonal=0, name=None):
    return primitive_call(lambda a: jnp.triu(a, diagonal), x, name="triu")


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1:
            d = jnp.diag(a, offset)
            if padding_value != 0:
                mask = jnp.eye(d.shape[0], dtype=bool)
                mask = jnp.roll(mask, offset, axis=1) if offset else mask
            return d if padding_value == 0 else jnp.where(
                jnp.eye(*d.shape, k=0, dtype=bool), d, padding_value
            )
        return jnp.diagonal(a, offset)

    return primitive_call(f, x, name="diag")


def diagflat(x, offset=0, name=None):
    return primitive_call(lambda a: jnp.diagflat(a, offset), x, name="diagflat")


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = jnp.meshgrid(*[a._value if isinstance(a, Tensor) else a for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    src = x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if output is not None:
        output._value = jnp.asarray(src, dtype=output._value.dtype)
        return output
    return primitive_call(lambda a: a + 0, x, name="assign") if isinstance(x, Tensor) else Tensor(src)


def clone(x, name=None):
    return x.clone()


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def one_hot(x, num_classes, name=None):
    return primitive_call(
        lambda a: jnp.eye(num_classes, dtype=jnp.float32)[a.astype(jnp.int32)], x, name="one_hot"
    )


# ---- parity batch (reference: python/paddle/tensor/creation.py) ----
def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    import jax.numpy as jnp

    from ..core.dtype import to_jax_dtype

    def val(v):
        return float(v.numpy()) if hasattr(v, "numpy") else float(v)

    out = jnp.logspace(val(start), val(stop), int(num), base=float(base),
                       dtype=to_jax_dtype(dtype) or jnp.float32)
    from ..core.tensor import Tensor

    return Tensor(out)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    import jax.numpy as jnp

    from ..core.dtype import to_jax_dtype
    from ..core.tensor import Tensor

    col = row if col is None else col
    r, c = jnp.tril_indices(int(row), k=int(offset), m=int(col))
    return Tensor(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    import jax.numpy as jnp

    from ..core.dtype import to_jax_dtype
    from ..core.tensor import Tensor

    col = row if col is None else col
    r, c = jnp.triu_indices(int(row), k=int(offset), m=int(col))
    return Tensor(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


def complex(real, imag, name=None):
    from ..core.dispatch import primitive_call

    return primitive_call(lambda r, i: r + 1j * i, real, imag, name="complex")


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone learnable parameter (reference: paddle.create_parameter —
    layers/tensor.py create_parameter)."""
    from .. import nn

    helper = nn.Layer()
    return helper.create_parameter(list(shape), attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


__all__ += ["logspace", "tril_indices", "triu_indices", "complex",
            "create_parameter"]
