"""Math ops (reference: python/paddle/tensor/math.py; PHI math kernels)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
    "pow", "matmul", "sqrt", "rsqrt", "exp", "expm1", "log", "log2", "log10",
    "log1p", "abs", "neg", "sign", "sin", "cos", "tan", "sinh", "cosh", "tanh",
    "asin", "acos", "atan", "atan2", "floor", "ceil", "round", "trunc", "clip",
    "maximum", "minimum", "fmax", "fmin", "sum", "mean", "max", "min", "prod",
    "cumsum", "cumprod", "std", "var", "square", "reciprocal", "erf", "add_n",
    "logsumexp", "isnan", "isinf", "isfinite", "all", "any", "scale", "increment",
    "dot", "outer", "inner", "multiplex", "logit", "lerp", "rad2deg", "deg2rad",
    "amax", "amin", "nanmean", "nansum", "count_nonzero", "frac", "diff", "angle",
    "stanh", "multiply_", "add_", "clip_", "scale_", "subtract_",
]


def _wrap2(op_name, f):
    def op(x, y, name=None):
        if not isinstance(x, Tensor):
            x = Tensor(x)
        if isinstance(y, Tensor):
            return primitive_call(f, x, y, name=op_name)
        if isinstance(y, (np.ndarray, list, tuple)):
            return primitive_call(f, x, Tensor(y), name=op_name)
        # python scalar: keep it static (jax weak-type promotion preserves x dtype)
        return primitive_call(lambda a: f(a, y), x, name=op_name)

    op.__name__ = op_name
    return op


add = _wrap2("add", lambda a, b: a + b)
subtract = _wrap2("subtract", lambda a, b: a - b)
multiply = _wrap2("multiply", lambda a, b: a * b)
divide = _wrap2("divide", lambda a, b: a / b)
floor_divide = _wrap2("floor_divide", lambda a, b: jnp.floor_divide(a, b))
remainder = _wrap2("remainder", lambda a, b: jnp.remainder(a, b))
mod = remainder
maximum = _wrap2("maximum", jnp.maximum)
minimum = _wrap2("minimum", jnp.minimum)
fmax = _wrap2("fmax", jnp.fmax)
fmin = _wrap2("fmin", jnp.fmin)
atan2 = _wrap2("atan2", jnp.arctan2)


def pow(x, y, name=None):
    if isinstance(y, Tensor):
        return primitive_call(jnp.power, x, y, name="elementwise_pow")
    return primitive_call(lambda a: jnp.power(a, y), x, name="pow")


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return primitive_call(f, x, y, name="matmul",
                          attrs={"trans_x": bool(transpose_x), "trans_y": bool(transpose_y)})


def _wrap1(op_name, f):
    def op(x, name=None, **kw):
        if not isinstance(x, Tensor):
            x = Tensor(x)
        return primitive_call(f, x, name=op_name)

    op.__name__ = op_name
    return op


sqrt = _wrap1("sqrt", jnp.sqrt)
rsqrt = _wrap1("rsqrt", lambda a: jax.lax.rsqrt(a))
exp = _wrap1("exp", jnp.exp)
expm1 = _wrap1("expm1", jnp.expm1)
log = _wrap1("log", jnp.log)
log2 = _wrap1("log2", jnp.log2)
log10 = _wrap1("log10", jnp.log10)
log1p = _wrap1("log1p", jnp.log1p)
abs = _wrap1("abs", jnp.abs)
neg = _wrap1("neg", jnp.negative)
sign = _wrap1("sign", jnp.sign)
sin = _wrap1("sin", jnp.sin)
cos = _wrap1("cos", jnp.cos)
tan = _wrap1("tan", jnp.tan)
sinh = _wrap1("sinh", jnp.sinh)
cosh = _wrap1("cosh", jnp.cosh)
tanh = _wrap1("tanh", jnp.tanh)
asin = _wrap1("asin", jnp.arcsin)
acos = _wrap1("acos", jnp.arccos)
atan = _wrap1("atan", jnp.arctan)
floor = _wrap1("floor", jnp.floor)
ceil = _wrap1("ceil", jnp.ceil)
round = _wrap1("round", jnp.round)
trunc = _wrap1("trunc", jnp.trunc)
square = _wrap1("square", jnp.square)
reciprocal = _wrap1("reciprocal", lambda a: 1.0 / a)
erf = _wrap1("erf", jax.scipy.special.erf)
isnan = _wrap1("isnan", jnp.isnan)
isinf = _wrap1("isinf", jnp.isinf)
isfinite = _wrap1("isfinite", jnp.isfinite)
frac = _wrap1("frac", lambda a: a - jnp.trunc(a))
rad2deg = _wrap1("rad2deg", jnp.rad2deg)
deg2rad = _wrap1("deg2rad", jnp.deg2rad)
angle = _wrap1("angle", jnp.angle)
logit = _wrap1("logit", lambda a: jnp.log(a / (1 - a)))
stanh = _wrap1("stanh", lambda a: 1.7159 * jnp.tanh(0.66667 * a))


def clip(x, min=None, max=None, name=None):
    return primitive_call(lambda a: jnp.clip(a, min, max), x, name="clip")


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core.dtype import to_jax_dtype

    return primitive_call(
        lambda a: jnp.sum(a, axis=_axis(axis), dtype=to_jax_dtype(dtype), keepdims=keepdim),
        x,
        name="reduce_sum",
    )


def mean(x, axis=None, keepdim=False, name=None):
    return primitive_call(
        lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x, name="reduce_mean"
    )


def max(x, axis=None, keepdim=False, name=None):
    return primitive_call(
        lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x, name="reduce_max"
    )


def min(x, axis=None, keepdim=False, name=None):
    return primitive_call(
        lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x, name="reduce_min"
    )


amax, amin = max, min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    from ..core.dtype import to_jax_dtype

    return primitive_call(
        lambda a: jnp.prod(a, axis=_axis(axis), dtype=to_jax_dtype(dtype), keepdims=keepdim),
        x,
        name="reduce_prod",
    )


def nanmean(x, axis=None, keepdim=False, name=None):
    return primitive_call(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return primitive_call(lambda a: jnp.nansum(a, axis=_axis(axis), keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return primitive_call(
        lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64), x
    )


def cumsum(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a)
        return jnp.cumsum(a, axis=int(axis))

    return primitive_call(f, x, name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    return primitive_call(lambda a: jnp.cumprod(a, axis=dim), x, name="cumprod")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return primitive_call(
        lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        name="std",
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return primitive_call(
        lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        name="var",
    )


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return primitive_call(lambda xs: jax.tree_util.tree_reduce(jnp.add, list(xs)), list(inputs), name="add_n")


def logsumexp(x, axis=None, keepdim=False, name=None):
    return primitive_call(
        lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim),
        x,
        name="logsumexp",
    )


def all(x, axis=None, keepdim=False, name=None):
    return primitive_call(lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim), x, name="all")


def any(x, axis=None, keepdim=False, name=None):
    return primitive_call(lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim), x, name="any")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale

    def f(a):
        out = a * s + bias if bias_after_scale else (a + bias) * s
        return out

    return primitive_call(f, x, name="scale", attrs={
        "scale": float(s), "bias": float(bias),
        "bias_after_scale": bool(bias_after_scale)})


def increment(x, value=1.0, name=None):
    return _graft(x, add(x, value))


def dot(x, y, name=None):
    return primitive_call(
        lambda a, b: jnp.sum(a * b, axis=-1), x, y, name="dot"
    )


def outer(x, y, name=None):
    return primitive_call(lambda a, b: jnp.outer(a, b), x, y, name="outer")


def inner(x, y, name=None):
    return primitive_call(lambda a, b: jnp.inner(a, b), x, y, name="inner")


def multiplex(inputs, index, name=None):
    def f(xs, idx):
        stacked = jnp.stack(list(xs), axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))).astype(jnp.int32), axis=0
        )[0]

    return primitive_call(f, list(inputs), index, name="multiplex")


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return primitive_call(lambda a, b, w: a + w * (b - a), x, y, weight, name="lerp")
    return primitive_call(lambda a, b: a + weight * (b - a), x, y, name="lerp")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return primitive_call(lambda a: jnp.diff(a, n=n, axis=axis), x, name="diff")


# -------- in-place variants (paddle `op_` convention). Each computes through
# the traced op and GRAFTS the result's autograd node onto x — rebinding the
# buffer alone would make the tape treat the op as identity and skip its VJP
# (core/tape.py graft_inplace).
from ..core.tape import graft_inplace as _graft


def add_(x, y, name=None):
    return _graft(x, add(x, y))


def subtract_(x, y, name=None):
    return _graft(x, subtract(x, y))


def multiply_(x, y, name=None):
    return _graft(x, multiply(x, y))


def clip_(x, min=None, max=None, name=None):
    return _graft(x, clip(x, min, max))


def scale_(x, scale=1.0, bias=0.0, name=None):
    return _graft(x, globals()["scale"](x, scale=scale, bias=bias))


# ---- parity batch (reference: python/paddle/tensor/math.py __all__) ----
acosh = _wrap1("acosh", jnp.arccosh)
asinh = _wrap1("asinh", jnp.arcsinh)
atanh = _wrap1("atanh", jnp.arctanh)
conj = _wrap1("conj", jnp.conj)
digamma = _wrap1("digamma", jax.scipy.special.digamma)
lgamma = _wrap1("lgamma", jax.scipy.special.gammaln)
erfinv = _wrap1("erfinv", jax.scipy.special.erfinv)
real = _wrap1("real", jnp.real)
imag = _wrap1("imag", jnp.imag)
gcd = _wrap2("gcd", jnp.gcd)
lcm = _wrap2("lcm", jnp.lcm)
heaviside = _wrap2("heaviside", jnp.heaviside)
kron = _wrap2("kron", jnp.kron)
floor_mod = remainder


def tanh_(x, name=None):
    """In-place tanh (reference inplace contract: result written into x)."""
    from ..core.tape import graft_inplace

    return graft_inplace(x, tanh(x))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return primitive_call(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
        x, name="trace")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return primitive_call(
        lambda i, a, b: beta * i + alpha * (a @ b), input, x, y, name="addmm")


def quantile(x, q, axis=None, keepdim=False, name=None):
    qv = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return primitive_call(
        lambda a: jnp.quantile(a.astype(jnp.float64 if a.dtype == jnp.float64
                                        else jnp.float32),
                               qv, axis=_axis(axis), keepdims=keepdim),
        x, name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    qv = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return primitive_call(
        lambda a: jnp.nanquantile(a.astype(jnp.float64 if a.dtype == jnp.float64
                                           else jnp.float32),
                                  qv, axis=_axis(axis), keepdims=keepdim),
        x, name="nanquantile")


def renorm(x, p, axis, max_norm, name=None):
    """Scale each sub-tensor along `axis` so its p-norm is <= max_norm."""
    def f(a):
        red = tuple(i for i in range(a.ndim) if i != (axis % a.ndim))
        norms = jnp.sum(jnp.abs(a) ** p, axis=red, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * factor

    return primitive_call(f, x, name="renorm")


def rank(input, name=None):
    v = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    return Tensor(jnp.asarray(v.ndim, jnp.int32))


def is_complex(x):
    return jnp.issubdtype((x._value if isinstance(x, Tensor) else x).dtype,
                          jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype((x._value if isinstance(x, Tensor) else x).dtype,
                          jnp.floating)


def is_integer(x):
    return jnp.issubdtype((x._value if isinstance(x, Tensor) else x).dtype,
                          jnp.integer)


__all__ += [
    "acosh", "asinh", "atanh", "conj", "digamma", "lgamma", "erfinv", "real",
    "imag", "gcd", "lcm", "heaviside", "kron", "floor_mod", "tanh_", "trace",
    "addmm", "quantile", "nanquantile", "renorm", "rank", "is_complex",
    "is_floating_point", "is_integer",
]


def bincount(x, weights=None, minlength=0, name=None):
    """Histogram of non-negative ints (reference bincount op). The output
    length is data-dependent, so it is computed host-side (same reason the
    reference runs it on CPU for small inputs); inside jit, pass minlength
    covering the range instead."""
    import numpy as np_

    xv = np_.asarray(x._value if isinstance(x, Tensor) else x)
    wv = None if weights is None else np_.asarray(
        weights._value if isinstance(weights, Tensor) else weights)
    out = np_.bincount(xv.reshape(-1), weights=wv, minlength=int(minlength))
    return Tensor(jnp.asarray(out))


__all__ += ["bincount"]


def _inplace(fn, fn_name):
    def op(x, *args, name=None, **kw):
        return _graft(x, fn(x, *args, **kw))

    op.__name__ = fn_name
    return op


exp_ = _inplace(exp, "exp_")
ceil_ = _inplace(ceil, "ceil_")
floor_ = _inplace(floor, "floor_")
round_ = _inplace(round, "round_")
sqrt_ = _inplace(sqrt, "sqrt_")
rsqrt_ = _inplace(rsqrt, "rsqrt_")
reciprocal_ = _inplace(reciprocal, "reciprocal_")
erfinv_ = _inplace(erfinv, "erfinv_")
lerp_ = _inplace(lerp, "lerp_")

__all__ += ["exp_", "ceil_", "floor_", "round_", "sqrt_", "rsqrt_",
            "reciprocal_", "erfinv_", "lerp_"]
