"""Functional tensor op library (the PHI-kernel analog).

Reference analog: `/root/reference/paddle/phi/kernels/` (~150k LoC of CPU+CUDA
kernels) + `python/paddle/tensor/`. TPU-native: every op is a small pure-jax
lowering to XLA HLO; there are no per-backend kernels because XLA owns codegen.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
