"""Random ops — counter-based threefry (reference: python/paddle/tensor/random.py).

Every draw consumes a key from the RNG context (`core/rng.py`): stateful in eager
mode, functionally derived from the per-step base key under tracing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtype import get_default_dtype, to_jax_dtype
from ..core.rng import next_rng_key
from ..core.tensor import Tensor

__all__ = [
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "bernoulli", "multinomial", "randperm", "poisson",
    "uniform_", "normal_", "exponential_",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype):
    return to_jax_dtype(dtype or get_default_dtype())


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(next_rng_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_rng_key(), _shape(shape), _dt(dtype)))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(
        jax.random.randint(next_rng_key(), _shape(shape), low, high, to_jax_dtype(dtype))
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = to_jax_dtype(dtype) if dtype else x._value.dtype
    return Tensor(jax.random.randint(next_rng_key(), x._value.shape, low, high, dt))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_rng_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype), min, max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(next_rng_key(), shp) * s + m)
    return Tensor(jax.random.normal(next_rng_key(), _shape(shape)) * std + mean)


def bernoulli(x, name=None):
    return Tensor(
        jax.random.bernoulli(next_rng_key(), x._value, x._value.shape).astype(x._value.dtype)
    )


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = next_rng_key()
    logits = jnp.log(jnp.maximum(x._value, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1, shape=(*logits.shape[:-1], num_samples))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, logits.shape)
        out = jax.lax.top_k(logits + g, num_samples)[1]
    return Tensor(out.astype(jnp.int64))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_rng_key(), n).astype(to_jax_dtype(dtype)))


def poisson(x, name=None):
    return Tensor(jax.random.poisson(next_rng_key(), x._value).astype(x._value.dtype))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_rng_key()
    x._value = jax.random.uniform(key, x._value.shape, x._value.dtype, min, max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._value = jax.random.normal(next_rng_key(), x._value.shape, x._value.dtype) * std + mean
    return x


def exponential_(x, lam=1.0, name=None):
    x._value = jax.random.exponential(next_rng_key(), x._value.shape, x._value.dtype) / lam
    return x
