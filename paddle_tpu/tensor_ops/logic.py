"""Comparison & logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "allclose", "isclose", "equal_all", "is_empty", "is_tensor", "bitwise_and",
    "bitwise_or", "bitwise_xor", "bitwise_not",
]


def _to_t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _cmp(name, f):
    def op(x, y, name=None):
        import jax

        x = _to_t(x)
        if isinstance(y, Tensor):
            pass
        elif isinstance(y, (jax.Array, jax.core.Tracer, np.ndarray)):
            y = Tensor(y)  # keeps tracers traced (no np.asarray round-trip)
        else:
            # python scalar: compare in x's dtype (paddle semantics — a
            # default-dtype cast would corrupt float64 comparisons)
            y = Tensor(jnp.asarray(y, dtype=x._value.dtype))
        return primitive_call(lambda a, b: f(a, b), x.detach(), y.detach())

    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)


def logical_not(x, name=None):
    return primitive_call(jnp.logical_not, _to_t(x).detach())


def bitwise_not(x, name=None):
    return primitive_call(jnp.bitwise_not, _to_t(x).detach())


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(
        jnp.allclose(_to_t(x)._value, _to_t(y)._value, rtol=rtol, atol=atol, equal_nan=equal_nan)
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return primitive_call(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        _to_t(x).detach(),
        _to_t(y).detach(),
    )


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(_to_t(x)._value, _to_t(y)._value))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
