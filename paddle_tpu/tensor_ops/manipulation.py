"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor

__all__ = [
    "reshape", "reshape_", "transpose", "concat", "split", "stack", "unstack",
    "squeeze", "unsqueeze", "flatten", "expand", "expand_as", "tile",
    "broadcast_to", "gather", "gather_nd", "scatter", "scatter_nd_add", "slice",
    "index_select", "masked_select", "where", "roll", "flip", "chunk", "unbind",
    "cast", "t", "moveaxis", "tensordot", "repeat_interleave", "take_along_axis",
    "put_along_axis", "flatten_", "rot90", "as_complex", "as_real", "tolist",
    "strided_slice", "unique", "broadcast_shape", "squeeze_", "unsqueeze_",
]


def _to_t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def reshape(x, shape, name=None):
    shp = _shape_list(shape)
    # paddle semantics: 0 means copy the corresponding input dim
    shp = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shp))
    return primitive_call(lambda a: jnp.reshape(a, shp), _to_t(x), name="reshape",
                          attrs={"shape": [int(v) for v in shp]})


def reshape_(x, shape, name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(x, reshape(x, shape))


def transpose(x, perm, name=None):
    return primitive_call(lambda a: jnp.transpose(a, tuple(perm)), _to_t(x), name="transpose",
                          attrs={"axis": [int(v) for v in perm]})


def t(x, name=None):
    return primitive_call(lambda a: a.T, _to_t(x), name="t")


def moveaxis(x, source, destination, name=None):
    return primitive_call(lambda a: jnp.moveaxis(a, source, destination), _to_t(x))


def concat(x, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    ts = [_to_t(v) for v in x]
    return primitive_call(lambda xs: jnp.concatenate(list(xs), axis=axis), ts, name="concat",
                          attrs={"axis": axis})


def stack(x, axis=0, name=None):
    ts = [_to_t(v) for v in x]
    return primitive_call(lambda xs: jnp.stack(list(xs), axis=axis), ts, name="stack")


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    outs = primitive_call(
        lambda a: tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis)),
        _to_t(x),
        name="unstack",
    )
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    axis = axis % len(x.shape)  # negative axis: (slice,)*axis below needs >= 0
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(s) for s in num_or_sections]
        n_neg = [i for i, s in enumerate(sections) if s < 0]
        if n_neg:
            sections[n_neg[0]] = dim - sum(s for s in sections if s >= 0)
    offsets = np.cumsum([0] + sections[:-1]).tolist()
    outs = primitive_call(
        lambda a: tuple(
            jnp.asarray(a[(np.s_[:],) * axis + (np.s_[o : o + s],)]) for o, s in zip(offsets, sections)
        ),
        _to_t(x),
        name="split",
    )
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    return unstack(x, axis)


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a0 for a0 in ax if a.shape[a0] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a

    return primitive_call(f, _to_t(x), name="squeeze")


def squeeze_(x, axis=None, name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(x, squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    ax = tuple(int(a.item()) if isinstance(a, Tensor) else int(a) for a in ax)

    def f(a):
        for d in sorted(ax):
            a = jnp.expand_dims(a, d if d >= 0 else d + a.ndim + 1)
        return a

    return primitive_call(f, _to_t(x), name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(x, unsqueeze(x, axis))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis if start_axis >= 0 else start_axis + nd
        e = stop_axis if stop_axis >= 0 else stop_axis + nd
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1 :]
        return jnp.reshape(a, new_shape)

    return primitive_call(f, _to_t(x), name="flatten",
                          attrs={"start_axis": start_axis, "stop_axis": stop_axis})


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(x, flatten(x, start_axis, stop_axis))


def expand(x, shape, name=None):
    shp = _shape_list(shape)

    def f(a):
        tgt = tuple(
            a.shape[i - (len(shp) - a.ndim)] if s == -1 else s for i, s in enumerate(shp)
        )
        return jnp.broadcast_to(a, tgt)

    return primitive_call(f, _to_t(x), name="expand")


def expand_as(x, y, name=None):
    return primitive_call(lambda a, b: jnp.broadcast_to(a, b.shape), _to_t(x), _to_t(y).detach())


def broadcast_to(x, shape, name=None):
    return primitive_call(lambda a: jnp.broadcast_to(a, _shape_list(shape)), _to_t(x))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def tile(x, repeat_times, name=None):
    reps = _shape_list(repeat_times)
    return primitive_call(lambda a: jnp.tile(a, reps), _to_t(x), name="tile")


def gather(x, index, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return primitive_call(
        lambda a, i: jnp.take(a, i.astype(jnp.int32).reshape(-1), axis=axis),
        _to_t(x),
        _to_t(index),
        name="gather",
    )


def gather_nd(x, index, name=None):
    def f(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        return a[tuple(jnp.moveaxis(idx, -1, 0))] if k == a.ndim else a[
            tuple(jnp.moveaxis(idx, -1, 0))
        ]

    return primitive_call(f, _to_t(x), _to_t(index), name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, idx, upd):
        idx = idx.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[idx].set(upd)
        # reference accumulate mode (scatter kernel, overwrite=false):
        # target rows are ZEROED first, then all updates accumulate — the
        # original row value does not survive
        return a.at[idx].set(0).at[idx].add(upd)

    return primitive_call(f, _to_t(x), _to_t(index), _to_t(updates), name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, upd):
        idx = idx.astype(jnp.int32)
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)

    return primitive_call(f, _to_t(x), _to_t(index), _to_t(updates), name="scatter_nd_add")


def slice(x, axes, starts, ends, name=None):
    def f(a):
        idx = [np.s_[:]] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            st = int(st.item()) if isinstance(st, Tensor) else int(st)
            en = int(en.item()) if isinstance(en, Tensor) else int(en)
            idx[ax] = np.s_[st:en]
        return a[tuple(idx)]

    return primitive_call(f, _to_t(x), name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        idx = [np.s_[:]] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = np.s_[int(st) : int(en) : int(sd)]
        return a[tuple(idx)]

    return primitive_call(f, _to_t(x), name="strided_slice")


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def masked_select(x, mask, name=None):
    # dynamic-shape op: executes on host (XLA needs static shapes)
    xv, mv = np.asarray(_to_t(x)._value), np.asarray(_to_t(mask)._value)
    return Tensor(xv[mv])


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return tuple(Tensor(v) for v in np.nonzero(np.asarray(_to_t(condition)._value)))
    return primitive_call(
        lambda c, a, b: jnp.where(c, a, b), _to_t(condition).detach(), _to_t(x), _to_t(y), name="where"
    )


def roll(x, shifts, axis=None, name=None):
    return primitive_call(lambda a: jnp.roll(a, shifts, axis=axis), _to_t(x), name="roll")


def flip(x, axis, name=None):
    return primitive_call(lambda a: jnp.flip(a, axis=axis), _to_t(x), name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return primitive_call(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), _to_t(x))


def cast(x, dtype):
    return _to_t(x).astype(dtype)


def tensordot(x, y, axes=2, name=None):
    def _ax(axes):
        if isinstance(axes, Tensor):
            return axes.tolist()
        return axes

    return primitive_call(lambda a, b: jnp.tensordot(a, b, axes=_ax(axes)), _to_t(x), _to_t(y))


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats.numpy() if isinstance(repeats, Tensor) else repeats
    return primitive_call(lambda a: jnp.repeat(a, r, axis=axis), _to_t(x))


def take_along_axis(arr, indices, axis, name=None):
    return primitive_call(
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis),
        _to_t(arr),
        _to_t(indices),
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(a, i, v):
        i = i.astype(jnp.int32)
        if reduce == "add":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False) if False else _put(a, i, v, axis, add=True)
        return _put(a, i, v, axis, add=False)

    return primitive_call(f, _to_t(arr), _to_t(indices), _to_t(values))


def _put(a, i, v, axis, add):
    idx = list(jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing="ij"))
    idx[axis] = i
    v = jnp.broadcast_to(v, i.shape)
    return a.at[tuple(idx)].add(v) if add else a.at[tuple(idx)].set(v)


def as_complex(x, name=None):
    return primitive_call(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), _to_t(x))


def as_real(x, name=None):
    return primitive_call(lambda a: jnp.stack([a.real, a.imag], axis=-1), _to_t(x))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, name=None):
    # dynamic-shape: host computation
    res = np.unique(
        np.asarray(_to_t(x)._value),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if isinstance(res, tuple):
        return tuple(Tensor(r) for r in res)
    return Tensor(res)


def tolist(x):
    return _to_t(x).tolist()


import jax  # noqa: E402  (used by as_complex)


# ---- parity batch (reference: python/paddle/tensor/manipulation.py) ----
def broadcast_tensors(inputs, name=None):
    import jax.numpy as jnp

    vals = [x._value if isinstance(x, Tensor) else jnp.asarray(x) for x in inputs]
    shape = jnp.broadcast_shapes(*[v.shape for v in vals])
    return [Tensor(jnp.broadcast_to(v, shape)) for v in vals]


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return primitive_call(
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        x, name="diagonal")


def reverse(x, axis, name=None):
    return flip(x, axis, name=name)


def crop(x, shape=None, offsets=None, name=None):
    """Static crop: slice `shape` starting at `offsets` (defaults to 0s)."""
    def _ints(v, default, n):
        if v is None:
            return [default] * n
        if isinstance(v, Tensor):
            v = v.tolist()
        return [int(e) for e in v]

    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    n = xv.ndim
    shp = _ints(shape, -1, n)
    offs = _ints(offsets, 0, n)
    shp = [xv.shape[i] - offs[i] if s == -1 else s for i, s in enumerate(shp)]
    sl = tuple(slice(o, o + s) for o, s in zip(offs, shp))
    return primitive_call(lambda a: a[sl], x, name="crop")


def scatter_nd(index, updates, shape, name=None):
    """Zeros of `shape` with `updates` added at `index` (reference scatter_nd:
    duplicate indices accumulate)."""
    from ..core.dtype import to_jax_dtype  # noqa: F401 (parity with creation)

    def f(idx, upd):
        z = jnp.zeros(tuple(int(s) for s in shape), upd.dtype)
        return z.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)

    return primitive_call(f, index, updates, name="scatter_nd")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Map global ids to shard-local ids (reference: shard_index op — used by
    sharded embedding / parallel CE)."""
    if not 0 <= shard_id < nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range for {nshards} shards")
    size = (index_num + nshards - 1) // nshards

    def f(a):
        shard = a // size
        local = a % size
        return jnp.where(shard == shard_id, local, ignore_value)

    return primitive_call(f, input, name="shard_index")


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    """Collapse consecutive duplicates (reference unique_consecutive op).

    Host-side (NumPy) implementation: the output shape is data-dependent,
    which XLA cannot express — same reason the reference keeps it CPU-bound.
    """
    import numpy as np

    from ..core.dtype import to_jax_dtype

    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    if axis is None:
        flat = v.reshape(-1)
        keep = np.ones(flat.shape[0], bool)
        if flat.shape[0] > 1:
            keep[1:] = flat[1:] != flat[:-1]
        out = flat[keep]
        idx = np.cumsum(keep) - 1
        counts = np.bincount(idx, minlength=out.shape[0])
    else:
        moved = np.moveaxis(v, axis, 0)
        keep = np.ones(moved.shape[0], bool)
        if moved.shape[0] > 1:
            keep[1:] = (moved[1:] != moved[:-1]).reshape(moved.shape[0] - 1, -1).any(1)
        out = np.moveaxis(moved[keep], 0, axis)
        idx = np.cumsum(keep) - 1
        counts = np.bincount(idx, minlength=int(keep.sum()))
    res = [Tensor(jnp.asarray(out))]
    it = to_jax_dtype(dtype)
    if return_inverse:
        res.append(Tensor(jnp.asarray(idx.astype(it))))
    if return_counts:
        res.append(Tensor(jnp.asarray(counts.astype(it))))
    return res[0] if len(res) == 1 else tuple(res)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite=overwrite, name=name)
    if isinstance(x, Tensor):
        from ..core.tape import graft_inplace

        return graft_inplace(x, out)
    return out


__all__ += ["broadcast_tensors", "diagonal", "reverse", "crop", "scatter_nd",
            "shard_index", "unique_consecutive", "scatter_"]


def put_along_axis_(arr, indices, values, axis, reduce="assign", name=None):
    from ..core.tape import graft_inplace

    return graft_inplace(arr, put_along_axis(arr, indices, values, axis,
                                             reduce=reduce))


__all__ += ["put_along_axis_"]
