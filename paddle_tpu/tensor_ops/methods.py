"""Install tensor methods & operators on Tensor.

Reference analog: `python/paddle/tensor/__init__.py` monkey-patching +
`fluid/dygraph/math_op_patch.py`.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor
from . import creation, linalg, logic, manipulation, math, random, search
from .einsum import einsum  # noqa: F401


def _norm_idx(item):
    """Convert Tensor indices to arrays for jax indexing."""
    if isinstance(item, tuple):
        return tuple(_norm_idx(i) for i in item)
    if isinstance(item, Tensor):
        v = item._value
        if v.dtype == jnp.bool_:
            return np.asarray(v)  # boolean mask → host (dynamic shape)
        return v.astype(jnp.int32)
    if isinstance(item, (list, np.ndarray)):
        arr = np.asarray(item)
        return arr
    return item


def _getitem(self, item):
    idx = _norm_idx(item)

    def has_bool(i):
        if isinstance(i, tuple):
            return any(has_bool(x) for x in i)
        return isinstance(i, np.ndarray) and i.dtype == np.bool_

    if has_bool(idx):
        return Tensor(np.asarray(self._value)[idx])
    return primitive_call(lambda a: a[idx], self, name="getitem")


def _setitem(self, item, value):
    idx = _norm_idx(item)
    v = value._value if isinstance(value, Tensor) else value
    self._value = self._value.at[idx].set(jnp.asarray(v, dtype=self._value.dtype))


_BINOPS = {
    "__add__": math.add,
    "__radd__": lambda x, y: math.add(x, y),
    "__sub__": math.subtract,
    "__rsub__": lambda x, y: primitive_call(lambda a: y - a, x, name="rsub"),
    "__mul__": math.multiply,
    "__rmul__": lambda x, y: math.multiply(x, y),
    "__truediv__": math.divide,
    "__rtruediv__": lambda x, y: primitive_call(lambda a: y / a, x, name="rdiv"),
    "__floordiv__": math.floor_divide,
    "__mod__": math.remainder,
    "__pow__": math.pow,
    "__rpow__": lambda x, y: math.pow(Tensor(y), x),
    "__matmul__": math.matmul,
    "__eq__": logic.equal,
    "__ne__": logic.not_equal,
    "__lt__": logic.less_than,
    "__le__": logic.less_equal,
    "__gt__": logic.greater_than,
    "__ge__": logic.greater_equal,
    "__and__": logic.logical_and,
    "__or__": logic.logical_or,
    "__xor__": logic.logical_xor,
}

_METHODS = {}
for mod in (creation, math, manipulation, logic, search, linalg, random):
    for name in getattr(mod, "__all__", []):
        fn = getattr(mod, name)
        if callable(fn):
            _METHODS[name] = fn


def install():
    for name, fn in _BINOPS.items():
        setattr(Tensor, name, fn)
    Tensor.__neg__ = lambda self: math.neg(self)
    Tensor.__abs__ = lambda self: math.abs(self)
    Tensor.__invert__ = logic.logical_not
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    skip = {"to_tensor"}
    for name, fn in _METHODS.items():
        if name in skip or hasattr(Tensor, name):
            continue
        setattr(Tensor, name, fn)
    # method-name aliases matching paddle Tensor API
    Tensor.mm = math.matmul
    Tensor.dim = lambda self: self.ndim
    Tensor.rank = lambda self: Tensor(np.asarray(self.ndim, dtype=np.int32))
    Tensor.numel = lambda self: self.size
    Tensor.element_size = lambda self: np.dtype(np.asarray(self._value).dtype).itemsize
    # activation methods (reference Tensor patch: sigmoid/softmax live in
    # nn.functional but are also tensor methods)
    def _sigmoid(self, name=None):
        from ..nn import functional as F

        return F.sigmoid(self)

    def _softmax(self, axis=-1, name=None):
        from ..nn import functional as F

        return F.softmax(self, axis=axis)

    def _gradient(self):
        # legacy dygraph API: grad as numpy (varbase_patch_methods.gradient)
        return None if self.grad is None else np.asarray(self.grad.numpy())

    Tensor.sigmoid = _sigmoid
    Tensor.softmax = _softmax
    Tensor.gradient = _gradient
