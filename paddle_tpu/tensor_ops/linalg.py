"""Linear algebra ops (reference: python/paddle/tensor/linalg.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor

__all__ = ["norm", "bmm", "mm", "histogram", "mv", "matrix_power", "cholesky",
           "svd", "pinv", "solve", "triangular_solve", "qr", "eig", "eigvals",
           "matrix_rank", "det", "slogdet", "inv", "cross", "dist", "cond",
           "eigh", "eigvalsh", "lu", "lstsq", "cholesky_solve", "cov",
           "corrcoef"]


def _to_t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(a * a))
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=p, keepdims=keepdim)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.linalg.norm(a, ord=p if p != "fro" else None, axis=ax, keepdims=keepdim)

    return primitive_call(f, _to_t(x), name="norm")


def bmm(x, y, name=None):
    return primitive_call(lambda a, b: jnp.matmul(a, b), _to_t(x), _to_t(y), name="bmm")


def mm(input, mat2, name=None):
    return primitive_call(jnp.matmul, _to_t(input), _to_t(mat2), name="mm")


def mv(x, vec, name=None):
    return primitive_call(jnp.matmul, _to_t(x), _to_t(vec), name="mv")


def histogram(input, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        return jnp.histogram(a, bins=bins, range=(lo, hi))[0].astype(jnp.int64)

    return primitive_call(f, _to_t(input).detach())


def matrix_power(x, n, name=None):
    return primitive_call(lambda a: jnp.linalg.matrix_power(a, n), _to_t(x))


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return primitive_call(f, _to_t(x))


def svd(x, full_matrices=False, name=None):
    return primitive_call(lambda a: jnp.linalg.svd(a, full_matrices=full_matrices), _to_t(x))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return primitive_call(lambda a: jnp.linalg.pinv(a, rcond=rcond, hermitian=hermitian), _to_t(x))


def solve(x, y, name=None):
    return primitive_call(jnp.linalg.solve, _to_t(x), _to_t(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    import jax.scipy.linalg as jsl

    return primitive_call(
        lambda a, b: jsl.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        ),
        _to_t(x),
        _to_t(y),
    )


def qr(x, mode="reduced", name=None):
    return primitive_call(lambda a: jnp.linalg.qr(a, mode=mode), _to_t(x))


def eig(x, name=None):
    return primitive_call(lambda a: jnp.linalg.eig(a), _to_t(x).detach())


def eigvals(x, name=None):
    return primitive_call(lambda a: jnp.linalg.eigvals(a), _to_t(x).detach())


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return primitive_call(
        lambda a: jnp.linalg.matrix_rank(a, tol=tol).astype(jnp.int64), _to_t(x).detach()
    )


def det(x, name=None):
    return primitive_call(jnp.linalg.det, _to_t(x))


def slogdet(x, name=None):
    def f(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])

    return primitive_call(f, _to_t(x))


def inv(x, name=None):
    return primitive_call(jnp.linalg.inv, _to_t(x))


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis if axis != 9 else next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return primitive_call(f, _to_t(x), _to_t(y))


def dist(x, y, p=2, name=None):
    return primitive_call(
        lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), _to_t(x), _to_t(y)
    )


def cond(x, p=None, name=None):
    return primitive_call(lambda a: jnp.linalg.cond(a, p=p), _to_t(x).detach())


def eigh(x, UPLO="L", name=None):
    """reference: python/paddle/tensor/linalg.py eigh — symmetric/hermitian
    eigendecomposition (MXU-friendly: XLA's syevd). symmetrize_input=False:
    UPLO selects ONE triangle (paddle/numpy semantics), it does not average."""
    return primitive_call(
        lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO, symmetrize_input=False)),
        _to_t(x))


def eigvalsh(x, UPLO="L", name=None):
    return primitive_call(
        lambda a: jnp.linalg.eigh(a, UPLO=UPLO, symmetrize_input=False)[0],
        _to_t(x))


def lu(x, pivot=True, get_infos=False, name=None):
    """reference: tensor/linalg.py lu — returns (LU packed, pivots[, infos]).
    Pivots follow the paddle convention (1-based row swaps)."""
    if not pivot:
        raise NotImplementedError(
            "lu(pivot=False): XLA's LU is always partial-pivoted; returning "
            "a pivoted factorization under the no-pivot contract would be "
            "silently wrong")

    def g(a):
        import jax.scipy.linalg as jsl

        lu_packed, piv = jsl.lu_factor(a)
        out = (lu_packed, (piv + 1).astype(jnp.int32))
        if get_infos:
            out = out + (jnp.zeros((), jnp.int32),)
        return out

    return primitive_call(g, _to_t(x))


def lstsq(x, y, rcond=None, driver=None, name=None):
    """reference: tensor/linalg.py lstsq — least squares; returns
    (solution, residuals, rank, singular_values)."""

    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank.astype(jnp.int32), sv

    return primitive_call(f, _to_t(x), _to_t(y))


def cholesky_solve(x, y, upper=False, name=None):
    """reference: tensor/linalg.py cholesky_solve — solve A X = B given the
    Cholesky factor of A."""
    import jax

    def f(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)

    return primitive_call(f, _to_t(x), _to_t(y))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    """reference: tensor/linalg.py cov."""

    def f(a, *ws):
        fw = ws[0] if fweights is not None else None
        aw = (ws[1] if fweights is not None else ws[0]) if aweights is not None else None
        return jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)

    args = [_to_t(x)]
    if fweights is not None:
        args.append(_to_t(fweights).detach())
    if aweights is not None:
        args.append(_to_t(aweights).detach())
    return primitive_call(f, *args)


def corrcoef(x, rowvar=True, name=None):
    """reference: tensor/linalg.py corrcoef."""
    return primitive_call(lambda a: jnp.corrcoef(a, rowvar=rowvar), _to_t(x))


def inverse(x, name=None):
    """Alias of inv (reference keeps both names)."""
    return inv(x, name=name)


def multi_dot(tensors, name=None):
    """Chain matmul with optimal ordering (reference multi_dot op); jnp
    implements the dynamic-programming order selection."""
    return primitive_call(lambda *ts: jnp.linalg.multi_dot(list(ts)),
                          *tensors, name="multi_dot")


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack combined LU factors + pivots into (P, L, U) (reference
    lu_unpack op); batched like lu(). Disabled unpack flags return None in
    the corresponding slots (reference contract)."""
    def unpack(a, piv):
        m, n = a.shape[-2], a.shape[-1]
        L = U = P = None
        if unpack_ludata:
            k = min(m, n)
            L = jnp.tril(a[..., :, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
            U = jnp.triu(a[..., :k, :])
        if unpack_pivots:
            # pivots (1-indexed sequential swaps) -> permutation matrix
            def one(pv):
                perm = jnp.arange(m)
                for i in range(pv.shape[-1]):
                    j = pv[i] - 1
                    pi, pj = perm[i], perm[j]
                    perm = perm.at[i].set(pj).at[j].set(pi)
                return jnp.eye(m, dtype=a.dtype)[perm].T

            batch = piv.shape[:-1]
            if batch:
                P = jax.vmap(one)(piv.reshape((-1, piv.shape[-1])))
                P = P.reshape(batch + (m, m))
            else:
                P = one(piv)
        return P, L, U

    if unpack_ludata and unpack_pivots:
        return primitive_call(lambda a, p: unpack(a, p), lu_data, lu_pivots,
                              name="lu_unpack")
    # partial unpack: compute eagerly on the raw arrays (None slots are not
    # expressible through the traced multi-output op)
    from ..core.tensor import Tensor as _T

    a = lu_data._value if isinstance(lu_data, _T) else jnp.asarray(lu_data)
    piv = lu_pivots._value if isinstance(lu_pivots, _T) else jnp.asarray(lu_pivots)
    P, L, U = unpack(a, piv)
    wrap = lambda v: None if v is None else _T(v)  # noqa: E731
    return wrap(P), wrap(L), wrap(U)


__all__ += ["inverse", "multi_dot", "lu_unpack"]
