"""Search / sort ops (reference: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor

__all__ = ["argmax", "argmin", "argsort", "sort", "topk", "nonzero", "kthvalue",
           "mode", "index_sample", "searchsorted", "median"]


def _to_t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import to_jax_dtype

    return primitive_call(
        lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim).astype(to_jax_dtype(dtype)),
        _to_t(x).detach(),
    )


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import to_jax_dtype

    return primitive_call(
        lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim).astype(to_jax_dtype(dtype)),
        _to_t(x).detach(),
    )


def argsort(x, axis=-1, descending=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis)
        return jnp.flip(idx, axis=axis).astype(jnp.int64) if descending else idx.astype(jnp.int64)

    return primitive_call(f, _to_t(x).detach())


def sort(x, axis=-1, descending=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return primitive_call(f, _to_t(x))


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(k.item()) if isinstance(k, Tensor) else int(k)

    def f(a):
        ax = axis if axis >= 0 else axis + a.ndim
        if ax != a.ndim - 1:
            a_m = jnp.moveaxis(a, ax, -1)
        else:
            a_m = a
        vals, idx = jax.lax.top_k(a_m if largest else -a_m, k)
        if not largest:
            vals = -vals
        if ax != a.ndim - 1:
            vals = jnp.moveaxis(vals, -1, ax)
            idx = jnp.moveaxis(idx, -1, ax)
        return vals, idx.astype(jnp.int64)

    return primitive_call(f, _to_t(x))


def nonzero(x, as_tuple=False):
    res = np.nonzero(np.asarray(_to_t(x)._value))
    if as_tuple:
        return tuple(Tensor(r.reshape(-1, 1)) for r in res)
    return Tensor(np.stack(res, axis=1).astype(np.int64))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        vals = jnp.take(s, k - 1, axis=axis)
        idx = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx.astype(jnp.int64)

    return primitive_call(f, _to_t(x))


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis -> (values, indices). Tie-break
    matches the reference mode kernel (mode_op.h GetMode: scan over the
    SORTED axis keeps later runs on equal counts): among equally frequent
    values the LARGEST wins, and the index is its LAST occurrence."""

    def f(a):
        from jax import lax

        am = jnp.moveaxis(a, axis, -1)
        n = am.shape[-1]
        # O(n log n) run-length scan over the sorted axis (an n x n pairwise
        # count would blow memory at large n): within each run of equal
        # values the running count peaks at the run's end, so the LAST
        # position holding the global max count belongs to the largest of
        # the most-frequent values — the reference tie-break for free.
        s = jnp.sort(am, axis=-1)
        new_run = jnp.concatenate(
            [jnp.ones(am.shape[:-1] + (1,), bool),
             s[..., 1:] != s[..., :-1]], axis=-1)
        pos = jnp.arange(n, dtype=jnp.int32)
        run_start = lax.cummax(
            jnp.where(new_run, pos, 0).astype(jnp.int32), axis=am.ndim - 1)
        run_count = pos - run_start + 1
        best = (n - 1) - jnp.argmax(run_count[..., ::-1], axis=-1)
        vals = jnp.take_along_axis(s, best[..., None], axis=-1)[..., 0]
        idx = (n - 1) - jnp.argmax((am == vals[..., None])[..., ::-1],
                                   axis=-1)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx.astype(jnp.int64)

    return primitive_call(f, _to_t(x), name="mode")


def index_sample(x, index):
    return primitive_call(
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1),
        _to_t(x),
        _to_t(index),
    )


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    return primitive_call(
        lambda s, v: jnp.searchsorted(s, v, side=side).astype(
            jnp.int32 if out_int32 else jnp.int64
        ),
        _to_t(sorted_sequence).detach(),
        _to_t(values).detach(),
    )


def median(x, axis=None, keepdim=False, name=None):
    return primitive_call(lambda a: jnp.median(a, axis=axis, keepdims=keepdim), _to_t(x))


import jax  # noqa: E402
