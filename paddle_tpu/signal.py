"""paddle.signal — STFT / inverse STFT.

Reference analog: `python/paddle/signal.py` (stft/istft built on frame + fft
phi kernels `phi/kernels/cpu/frame_kernel.cc`). TPU-native: framing is a
gather/reshape XLA fuses away; FFT is HLO fft.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Split into overlapping frames (reference: signal.py frame:32; axis must
    be 0 or -1). axis=-1: (..., L) -> (..., frame_length, num_frames);
    axis=0: (L, ...) -> (num_frames, frame_length, ...)."""
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    xv = _v(x)
    if axis == 0:
        out = frame(Tensor(jnp.moveaxis(xv, 0, -1)), frame_length, hop_length)._value
        # (..., frame_length, num_frames) -> (num_frames, frame_length, ...)
        return Tensor(jnp.moveaxis(jnp.moveaxis(out, -1, 0), -1, 1))
    n = xv.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])
    out = xv[..., idx]  # (..., num_frames, frame_length)
    return Tensor(jnp.swapaxes(out, -1, -2))


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference: signal.py overlap_add:154; axis 0 or -1)."""
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    xv = _v(x)
    if axis == 0:
        # (num_frames, frame_length, ...) -> canonical (..., frame_length, num_frames)
        canon = jnp.moveaxis(jnp.moveaxis(xv, 1, -1), 0, -1)
        return Tensor(jnp.moveaxis(
            overlap_add(Tensor(canon), hop_length)._value, -1, 0))
    frame_length, num_frames = xv.shape[-2], xv.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    frames = jnp.swapaxes(xv, -1, -2)  # (..., num_frames, frame_length)
    lead = frames.shape[:-2]
    out = jnp.zeros(lead + (out_len,), xv.dtype)
    starts = hop_length * np.arange(num_frames)
    idx = starts[:, None] + np.arange(frame_length)[None, :]  # static indices
    flat_idx = jnp.asarray(idx.reshape(-1))
    out = out.at[..., flat_idx].add(frames.reshape(lead + (-1,)))
    return Tensor(out)


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    xv = _v(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones(win_length, xv.dtype)
    else:
        win = _v(window).astype(xv.dtype)
    if win_length < n_fft:  # center-pad window to n_fft
        lp = (n_fft - win_length) // 2
        win = jnp.pad(win, (lp, n_fft - win_length - lp))
    if center:
        pad = n_fft // 2
        cfg = [(0, 0)] * (xv.ndim - 1) + [(pad, pad)]
        xv = jnp.pad(xv, cfg, mode=pad_mode)
    frames = frame(Tensor(xv), n_fft, hop_length)._value  # (..., n_fft, num_frames)
    frames = jnp.swapaxes(frames, -1, -2) * win  # (..., num_frames, n_fft)
    spec = jnp.fft.rfft(frames, axis=-1) if onesided else jnp.fft.fft(frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return Tensor(jnp.swapaxes(spec, -1, -2))  # (..., freq, num_frames)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    xv = _v(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones(win_length, jnp.float64)
    else:
        win = _v(window).astype(jnp.float64)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        win = jnp.pad(win, (lp, n_fft - win_length - lp))
    spec = jnp.swapaxes(xv, -1, -2)  # (..., num_frames, freq)
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float64))
    frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
              else jnp.fft.ifft(spec, axis=-1).real)
    frames = frames * win
    y = overlap_add(Tensor(jnp.swapaxes(frames, -1, -2)), hop_length)._value
    wsq = overlap_add(
        Tensor(jnp.tile((win * win)[:, None], (1, xv.shape[-1]))), hop_length
    )._value
    y = y / jnp.where(wsq > 1e-11, wsq, 1.0)
    if center:
        pad = n_fft // 2
        y = y[..., pad:-pad] if length is None else y[..., pad:pad + length]
    elif length is not None:
        y = y[..., :length]
    return Tensor(y)
