"""paddle.signal — STFT / inverse STFT.

Reference analog: `python/paddle/signal.py` (stft/istft built on frame + fft
phi kernels `phi/kernels/cpu/frame_kernel.cc`). TPU-native: framing is a
gather/reshape XLA fuses away; FFT is HLO fft. Every public function is a
single pure-jax lowering dispatched through `primitive_call`, so gradients
flow through the eager tape (ADVICE r1: the previous Tensor(...) wrappers
silently stopped them) and each op records as one tape node.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.dispatch import primitive_call
from .core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _shape(x):
    return tuple(x._value.shape) if isinstance(x, Tensor) else np.shape(x)


def _frame_raw(xv, frame_length, hop_length):
    """(..., L) -> (..., frame_length, num_frames)"""
    n = xv.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])
    out = xv[..., idx]  # (..., num_frames, frame_length)
    return jnp.swapaxes(out, -1, -2)


def _overlap_add_raw(xv, hop_length):
    """(..., frame_length, num_frames) -> (..., out_len)"""
    frame_length, num_frames = xv.shape[-2], xv.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    frames = jnp.swapaxes(xv, -1, -2)  # (..., num_frames, frame_length)
    lead = frames.shape[:-2]
    out = jnp.zeros(lead + (out_len,), xv.dtype)
    starts = hop_length * np.arange(num_frames)
    idx = starts[:, None] + np.arange(frame_length)[None, :]  # static indices
    flat_idx = jnp.asarray(idx.reshape(-1))
    return out.at[..., flat_idx].add(frames.reshape(lead + (-1,)))


def _validate_frame(n, frame_length, hop_length):
    """reference signal.py frame:32 input checks."""
    if hop_length <= 0:
        raise ValueError(
            f"Attribute hop_length should be greater than 0, but got {hop_length}."
        )
    if frame_length > n:
        raise ValueError(
            f"Attribute frame_length should be less than or equal to input "
            f"length along the framing axis ({n}), but got {frame_length}."
        )


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Split into overlapping frames (reference: signal.py frame:32; axis must
    be 0 or -1). axis=-1: (..., L) -> (..., frame_length, num_frames);
    axis=0: (L, ...) -> (num_frames, frame_length, ...)."""
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    shape = _shape(x)
    _validate_frame(shape[0] if axis == 0 else shape[-1], frame_length, hop_length)

    def raw(xv):
        if axis == 0:
            out = _frame_raw(jnp.moveaxis(xv, 0, -1), frame_length, hop_length)
            # (..., frame_length, num_frames) -> (num_frames, frame_length, ...)
            return jnp.moveaxis(jnp.moveaxis(out, -1, 0), -1, 1)
        return _frame_raw(xv, frame_length, hop_length)

    return primitive_call(raw, x, name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference: signal.py overlap_add:154; axis 0 or -1)."""
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    if hop_length <= 0:
        raise ValueError(
            f"Attribute hop_length should be greater than 0, but got {hop_length}."
        )

    def raw(xv):
        if axis == 0:
            # (num_frames, frame_length, ...) -> canonical
            canon = jnp.moveaxis(jnp.moveaxis(xv, 1, -1), 0, -1)
            return jnp.moveaxis(_overlap_add_raw(canon, hop_length), -1, 0)
        return _overlap_add_raw(xv, hop_length)

    return primitive_call(raw, x, name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    n = _shape(x)[-1]
    _validate_frame(n + (n_fft if center else 0), n_fft, hop_length)

    def raw(xv, win_in):
        if win_in is None:
            win = jnp.ones(win_length, xv.dtype)
        else:
            win = win_in.astype(xv.dtype)
        if win_length < n_fft:  # center-pad window to n_fft
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        if center:
            pad = n_fft // 2
            cfg = [(0, 0)] * (xv.ndim - 1) + [(pad, pad)]
            xv = jnp.pad(xv, cfg, mode=pad_mode)
        frames = _frame_raw(xv, n_fft, hop_length)  # (..., n_fft, num_frames)
        frames = jnp.swapaxes(frames, -1, -2) * win  # (..., num_frames, n_fft)
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # (..., freq, num_frames)

    if window is None:
        return primitive_call(lambda xv: raw(xv, None), x, name="stft")
    return primitive_call(raw, x, window, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    num_frames = _shape(x)[-1]

    def raw(xv, win_in):
        if win_in is None:
            win = jnp.ones(win_length, jnp.float64)
        else:
            win = win_in.astype(jnp.float64)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        spec = jnp.swapaxes(xv, -1, -2)  # (..., num_frames, freq)
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float64))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(spec, axis=-1).real)
        frames = frames * win
        y = _overlap_add_raw(jnp.swapaxes(frames, -1, -2), hop_length)
        wsq = _overlap_add_raw(
            jnp.tile((win * win)[:, None], (1, num_frames)), hop_length
        )
        y = y / jnp.where(wsq > 1e-11, wsq, 1.0)
        if center:
            pad = n_fft // 2
            y = y[..., pad:-pad] if length is None else y[..., pad:pad + length]
        elif length is not None:
            y = y[..., :length]
        return y

    if window is None:
        return primitive_call(lambda xv: raw(xv, None), x, name="istft")
    return primitive_call(raw, x, window, name="istft")
