"""Static-graph control flow: cond / while_loop / case / switch_case.

Reference analog: `paddle/fluid/operators/controlflow/while_op.cc:50` and
`conditional_block_op.cc` — ops whose Attrs carry a sub-BlockDesc executed by a
nested executor. TPU-native redesign: the branch/body is traced once into a
sub-Block of the Program (the same `primitive_call` static hook records its
ops), then ONE Operator is appended whose pure-jax lowering wraps
`lax.cond` / `lax.while_loop` around a replay of that sub-Block. XLA sees HLO
Conditional/While — compiler-friendly control flow with no data-dependent
Python (survey hard-part #4).

In dygraph mode the same APIs execute eagerly (python if / while), matching the
reference's dygraph passthrough (`layers/control_flow.py` cond:1214).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dtype import convert_dtype
from ..utils.misc import unique_name
from .mode import in_static_mode
from .program import (
    Block,
    Operator,
    Variable,
    _flat_inputs,
    default_main_program,
)

__all__ = ["cond", "while_loop", "case", "switch_case"]


# ------------------------------------------------------------ sub-block tracing
def _trace_subblock(fn, formals):
    """Record fn(*formals)'s ops into a fresh sub-Block; return (block, outs).

    `formals` are fresh placeholder Variables standing for values supplied at
    run time (loop carry / branch operands) — the analog of the sub-BlockDesc's
    input vars in the reference's conditional_block/while ops.
    """
    prog = default_main_program()
    block = Block(prog, len(prog.blocks), prog.current_block_idx)
    prog.blocks.append(block)
    prev = prog.current_block_idx
    prog.current_block_idx = block.idx
    try:
        outs = fn(*formals)
    finally:
        prog.current_block_idx = prev
    return block, outs


def _block_externals(block, formals, extra_reads=()):
    """Values a sub-Block reads from outside it: outer Variables and concrete
    Tensors (captured weights). These become inputs of the combined op so the
    Executor resolves them (substituting trained parameter values).
    `extra_reads`: values the block returns (they count as reads — external
    only when not produced by the block itself)."""
    defined = {id(f) for f in formals}
    for op in block.ops:
        for o in op.outputs:
            defined.add(id(o))
    ext, seen = [], set()
    reads = [t for op in block.ops for t in _flat_inputs(op.inputs)]
    reads += [t for t in extra_reads]
    for t in reads:
        if isinstance(t, Tensor) and id(t) not in defined and id(t) not in seen:
            seen.add(id(t))
            ext.append(t)
    return ext


def _replay_block(block, env):
    """Execute a sub-Block's op tape under `env` (id -> array). The ops' fns
    are pure jax closures, so this composes under lax.cond/while tracing."""

    def resolve(x):
        if isinstance(x, Tensor):  # Variable is a Tensor subclass
            if id(x) in env:
                return env[id(x)]
            if isinstance(x, Variable):
                raise KeyError(
                    f"control-flow sub-block read {x.name!r} which has no value "
                    "in the enclosing scope"
                )
            return x._value  # concrete Tensor not routed as external (frozen)
        if isinstance(x, (list, tuple)):
            return type(x)(resolve(i) for i in x)
        return x

    for op in block.ops:
        ins = [resolve(i) for i in op.inputs]
        out = op.fn(*ins)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        for var, val in zip(op.outputs, outs):
            env[id(var)] = val
    return env


def _aval_of(x):
    if isinstance(x, Variable):
        return x._value
    if isinstance(x, Tensor):
        return jax.ShapeDtypeStruct(tuple(x._value.shape), x._value.dtype)
    a = jnp.asarray(np.asarray(x))
    return jax.ShapeDtypeStruct(a.shape, a.dtype)


def _placeholder_like(x, tag):
    av = _aval_of(x)
    return Variable(av.shape, convert_dtype(av.dtype),
                    name=unique_name.generate(tag), stop_gradient=False)


def _flatten_struct(out):
    """branch output -> (flat list, structure tag)"""
    if isinstance(out, (tuple, list)):
        return list(out), ("seq", type(out), len(out))
    return [out], ("one",)


def _pack_struct(flat, struct):
    if struct[0] == "one":
        return flat[0]
    return struct[1](flat)


def _as_value(x):
    return x._value if isinstance(x, Tensor) else x


def c_out_t0(c_out):
    return c_out[0] if isinstance(c_out, (tuple, list)) else c_out


# ---------------------------------------------------------------------- cond
def cond(pred, true_fn=None, false_fn=None, name=None):
    """reference: paddle.static.nn.cond (layers/control_flow.py:1214) lowering
    to conditional_block ops; here: one Operator wrapping lax.cond."""
    if not in_static_mode():
        p = bool(np.asarray(pred.numpy() if isinstance(pred, Tensor) else pred))
        return true_fn() if p else (false_fn() if false_fn is not None else None)

    t_block, t_out = _trace_subblock(true_fn, ())
    f_block, f_out = _trace_subblock(false_fn, ())
    t_flat, t_struct = _flatten_struct(t_out)
    f_flat, f_struct = _flatten_struct(f_out)
    if len(t_flat) != len(f_flat):
        raise ValueError(
            f"cond: true_fn returned {len(t_flat)} values, false_fn {len(f_flat)}"
        )

    t_ext = _block_externals(t_block, (), extra_reads=t_flat)
    f_ext = _block_externals(f_block, (), extra_reads=f_flat)
    ext, seen = [], set()
    for t in t_ext + f_ext:
        if id(t) not in seen:
            seen.add(id(t))
            ext.append(t)

    t_ids = [id(o) if isinstance(o, Tensor) else None for o in t_flat]
    f_ids = [id(o) if isinstance(o, Tensor) else None for o in f_flat]
    t_const = [None if isinstance(o, Tensor) else o for o in t_flat]
    f_const = [None if isinstance(o, Tensor) else o for o in f_flat]

    def op_fn(pred_v, *ext_vals):
        base = {id(e): v for e, v in zip(ext, ext_vals)}

        def run(block, out_ids, consts):
            env = dict(base)
            _replay_block(block, env)
            return tuple(
                jnp.asarray(env[i] if i is not None else c)
                for i, c in zip(out_ids, consts)
            )

        return jax.lax.cond(
            jnp.reshape(jnp.asarray(pred_v), ()).astype(bool),
            lambda vals: run(t_block, t_ids, t_const),
            lambda vals: run(f_block, f_ids, f_const),
            ext_vals,
        )

    block = default_main_program().current_block()
    out_avals = jax.eval_shape(
        op_fn, _aval_of(pred), *[_aval_of(e) for e in ext]
    )
    outputs = [
        block.create_var(o.shape, convert_dtype(o.dtype),
                         name=unique_name.generate("cond"))
        for o in out_avals
    ]
    for o in outputs:
        o.stop_gradient = False
    block.append_op(Operator("conditional_block", op_fn, [pred] + ext, outputs))
    return _pack_struct(list(outputs), t_struct)


# ----------------------------------------------------------------- while_loop
def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """reference: paddle.static.nn.while_loop (layers/control_flow.py:1076) →
    while_op (while_op.cc:50); here: one Operator wrapping lax.while_loop.

    Loop-carried values must keep shape/dtype across iterations (the same
    invariant the reference enforces on the sub-block's output vars)."""
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("loop_vars must be a non-empty list/tuple")

    if not in_static_mode():
        vals = list(loop_vars)
        while bool(np.asarray(_as_value(cond_fn(*vals)))):
            vals = list(body_fn(*vals))
        return vals

    formals = [_placeholder_like(v, "while_in") for v in loop_vars]
    c_block, c_out = _trace_subblock(cond_fn, formals)
    b_block, b_out = _trace_subblock(body_fn, formals)
    b_flat, _ = _flatten_struct(b_out)
    if len(b_flat) != len(loop_vars):
        raise ValueError(
            f"while_loop: body returned {len(b_flat)} values for "
            f"{len(loop_vars)} loop_vars"
        )
    for v, o in zip(loop_vars, b_flat):
        va, oa = _aval_of(v), _aval_of(o)
        if tuple(va.shape) != tuple(oa.shape) or va.dtype != oa.dtype:
            raise ValueError(
                "while_loop: body output must match loop var shape/dtype, got "
                f"{oa.shape}/{oa.dtype} vs {va.shape}/{va.dtype}"
            )

    ext, seen = [], set()
    for t in (_block_externals(c_block, formals, extra_reads=[c_out_t0(c_out)])
              + _block_externals(b_block, formals, extra_reads=b_flat)):
        if id(t) not in seen:
            seen.add(id(t))
            ext.append(t)

    n = len(loop_vars)
    c_out_t = c_out_t0(c_out)
    b_ids = [id(o) if isinstance(o, Tensor) else None for o in b_flat]
    b_const = [None if isinstance(o, Tensor) else o for o in b_flat]
    formal_ids = [id(f) for f in formals]

    def op_fn(*ins):
        init = tuple(jnp.asarray(v) for v in ins[:n])
        ext_vals = ins[n:]
        base = {id(e): v for e, v in zip(ext, ext_vals)}

        def cond_l(carry):
            env = dict(base)
            env.update(zip(formal_ids, carry))
            # formals may flow through unchanged into the predicate
            _replay_block(c_block, env)
            pv = env[id(c_out_t)] if isinstance(c_out_t, Tensor) else c_out_t
            return jnp.reshape(jnp.asarray(pv), ()).astype(bool)

        def body_l(carry):
            env = dict(base)
            env.update(zip(formal_ids, carry))
            _replay_block(b_block, env)
            return tuple(
                jnp.asarray(env[i]).astype(c.dtype) if i is not None else
                jnp.asarray(cst)
                for i, c, cst in zip(b_ids, carry, b_const)
            )

        return jax.lax.while_loop(cond_l, body_l, init)

    block = default_main_program().current_block()
    out_avals = jax.eval_shape(op_fn, *[_aval_of(x) for x in
                                        list(loop_vars) + ext])
    outputs = [
        block.create_var(o.shape, convert_dtype(o.dtype),
                         name=unique_name.generate("while"))
        for o in out_avals
    ]
    for o in outputs:
        o.stop_gradient = False
    block.append_op(Operator("while", op_fn, list(loop_vars) + ext, outputs))
    return list(outputs)


# ----------------------------------------------------------------------- case
def case(pred_fn_pairs, default=None, name=None):
    """reference: paddle.static.nn.case — first true predicate wins."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")

    def build(pairs):
        (pred, fn) = pairs[0]
        if len(pairs) == 1:
            if default is None:
                return cond(pred, fn, fn)
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(pairs[1:]))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: paddle.static.nn.switch_case — dispatch on an int index.
    Lowers through lax.switch for a flat HLO Conditional."""
    pairs = sorted(branch_fns.items()) if isinstance(branch_fns, dict) else \
        list(enumerate(branch_fns))

    if not in_static_mode():
        idx = int(np.asarray(_as_value(branch_index)))
        for k, fn in pairs:
            if k == idx:
                return fn()
        if default is None:
            return pairs[-1][1]()
        return default()

    def build(ps):
        k, fn = ps[0]
        import paddle_tpu as paddle

        eq = paddle.equal(branch_index, paddle.to_tensor(np.int64(k)))
        if len(ps) == 1:
            fallback = default if default is not None else pairs[-1][1]
            return cond(eq, fn, fallback)
        return cond(eq, fn, lambda: build(ps[1:]))

    return build(pairs)
