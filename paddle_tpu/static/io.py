"""Static-graph inference model save/load.

Reference analog: `python/paddle/static/io.py` (`save_inference_model`,
`load_inference_model`) and `python/paddle/fluid/io.py` — the reference prunes
the Program to the feed→fetch subgraph and serializes a ProgramDesc protobuf
plus persistable variables (via `save_combine_op`).

TPU-native design: the deployable artifact is a *compiled computation*, not an
op graph. `save_inference_model` lowers the Program's feed→fetch slice to ONE
XLA computation (weights baked in as constants — the IPU "weights stay on
device" model, survey §3.5) and serializes it with `jax.export` (StableHLO
bytes, forward-compatible). The `.pdmodel` file holds the serialized module +
feed/fetch metadata; `.pdiparams` holds the raw weights (numpy pickle) so the
model remains editable/finetunable after load.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as rng_mod
from ..core import tape as tape_mod
from ..core.tensor import Tensor
from .program import Program, Variable, default_main_program

_MAGIC = "paddle_tpu.inference.v1"


def _lower_forward(program: Program, feed_vars, fetch_vars):
    """Pure fn (feed arrays in feed_vars order) -> fetch arrays, params baked."""
    params = program.captured_params()
    param_arrays = [p._value for p in params]

    def fwd(*feed_arrays):
        env = {id(p): a for p, a in zip(params, param_arrays)}
        for v, a in zip(feed_vars, feed_arrays):
            env[id(v)] = a

        def resolve(x):
            if isinstance(x, Variable):
                if id(x) in env:
                    return env[id(x)]
                raise KeyError(f"Variable {x.name} has no value (missing feed?)")
            if isinstance(x, Tensor):
                return env.get(id(x), x._value)
            if isinstance(x, (list, tuple)):
                return type(x)(resolve(i) for i in x)
            return x

        with tape_mod.no_grad(), rng_mod.trace_rng_scope(jax.random.PRNGKey(0)):
            for op in program.all_ops():
                ins = [resolve(i) for i in op.inputs]
                out = op.fn(*ins)
                outs = list(out) if isinstance(out, (tuple, list)) else [out]
                for var, val in zip(op.outputs, outs):
                    env[id(var)] = val
        return tuple(env[id(f)] for f in fetch_vars)

    return fwd, params


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, program_format="stablehlo", **kwargs):
    """reference: python/paddle/static/io.py save_inference_model.

    program_format="stablehlo" (default) writes the TPU-native compiled
    artifact; "pdmodel" writes a REAL ProgramDesc protobuf + LoDTensor
    params pair consumable by actual Paddle inference stacks
    (static/pdmodel_export.py)."""
    if program_format == "pdmodel":
        from .pdmodel_export import save_inference_model_pdmodel

        return save_inference_model_pdmodel(
            path_prefix, feed_vars, fetch_vars, program=program)
    program = program or default_main_program()
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    fwd, params = _lower_forward(program, feed_vars, fetch_vars)

    avals = [jax.ShapeDtypeStruct(tuple(v._value.shape), v._value.dtype)
             for v in feed_vars]
    from jax import export as jexport

    exported = jexport.export(jax.jit(fwd))(*avals)
    blob = exported.serialize()

    meta = {
        "magic": _MAGIC,
        "feed_names": [v.name for v in feed_vars],
        "feed_shapes": [tuple(v._value.shape) for v in feed_vars],
        "feed_dtypes": [str(v._value.dtype) for v in feed_vars],
        "fetch_names": [f.name for f in fetch_vars],
        "stablehlo": blob,
    }
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(meta, f, protocol=4)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump([np.asarray(p._value) for p in params], f, protocol=4)
    return path_prefix + ".pdmodel"


class _LoadedInferenceProgram:
    """Stands in for the (program, feed_names, fetch_vars) triple the reference
    returns: Executor.run detects `_exported_call` and dispatches to it."""

    def __init__(self, meta):
        from jax import export as jexport

        self._meta = meta
        self._exported = jexport.deserialize(meta["stablehlo"])
        self.feed_names = meta["feed_names"]
        self.fetch_names = meta["fetch_names"]

    def _exported_call(self, feed: dict):
        args = []
        for name, shape, dt in zip(self._meta["feed_names"],
                                   self._meta["feed_shapes"],
                                   self._meta["feed_dtypes"]):
            if name not in feed:
                raise KeyError(f"missing feed {name!r}")
            a = feed[name]
            a = a.numpy() if isinstance(a, Tensor) else np.asarray(a)
            args.append(jnp.asarray(a, dtype=dt))
        out = self._exported.call(*args)
        return list(out) if isinstance(out, (tuple, list)) else [out]


class _LoadedPdModelProgram:
    """Executor-compatible view of a REAL Paddle ProgramDesc model."""

    def __init__(self, prog):
        self._prog = prog
        self.feed_names = prog.feed_names
        self.fetch_names = prog.fetch_names
        # Predictor reads feed specs through _meta (same shape as the
        # StableHLO loader's)
        self._meta = {"feed_names": prog.feed_names,
                      "feed_shapes": prog.feed_shapes,
                      "feed_dtypes": prog.feed_dtypes,
                      "fetch_names": prog.fetch_names}

    def _exported_call(self, feed: dict):
        clean = {}
        for name in self.feed_names:
            if name not in feed:
                raise KeyError(f"missing feed {name!r}")
            a = feed[name]
            clean[name] = a.numpy() if isinstance(a, Tensor) else \
                np.asarray(a)
        return self._prog.run(clean)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """reference: python/paddle/static/io.py load_inference_model.
    Returns (program-like, feed_names, fetch_names). Accepts BOTH this
    framework's StableHLO export and a REAL PaddlePaddle
    .pdmodel/.pdiparams pair (ProgramDesc protobuf — inference/pdmodel.py)."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        head = f.read(2)
    if head[:1] != b"\x80":  # not a pickle: real ProgramDesc protobuf
        from ..inference.pdmodel import load_pdmodel

        prog = _LoadedPdModelProgram(load_pdmodel(
            path_prefix, params_file=kwargs.get("params_file"),
            ir_optim=kwargs.get("ir_optim", True)))
        return prog, prog.feed_names, prog.fetch_names
    with open(path_prefix + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    if meta.get("magic") not in (_MAGIC, "paddle_tpu.jit.v1"):
        raise ValueError(f"{path_prefix}.pdmodel is not a paddle_tpu inference model")
    prog = _LoadedInferenceProgram(meta)
    return prog, prog.feed_names, prog.fetch_names


def serialize_program(program=None, feed_vars=(), fetch_vars=()):
    """ProgramDesc protobuf bytes (reference: static/io.py
    serialize_program). Ops must be in the pdmodel emitter set
    (static/pdmodel_export.py); params are not included (use
    save_inference_model for the full artifact pair)."""
    from .pdmodel_export import serialize_program_desc

    program = program or default_main_program()
    blob, _ = serialize_program_desc(program, list(feed_vars),
                                     list(fetch_vars))
    return blob


def deserialize_program(data):  # pragma: no cover - parity shim
    raise NotImplementedError(
        "paddle_tpu programs serialize as compiled StableHLO via "
        "save_inference_model, not as op-graph protobufs"
    )
