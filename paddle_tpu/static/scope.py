"""Hierarchical runtime Scope.

Reference analog: framework::Scope (/root/reference/paddle/fluid/framework/
scope.h:78) holding name -> Variable (variable.h:26), with parent-chain lookup
(FindVar walks ancestors), child scopes (NewScope), and kid teardown
(DropKids). The executors resolve every op's vars through a scope.

TPU-native use: eager/jit paths don't need scopes (python closures carry
state), but the static Executor honors one for feed/fetch persistence and the
PS/dataset workers use child scopes per thread — same contract as the
reference.
"""
from __future__ import annotations

from ..core.errors import NotFoundError

__all__ = ["Scope", "global_scope", "scope_guard"]


class Scope:
    def __init__(self, parent: "Scope | None" = None):
        self._vars: dict[str, object] = {}
        self._parent = parent
        self._kids: list[Scope] = []

    # ------------------------------------------------------------- variables
    def var(self, name: str):
        """Find-or-create in THIS scope (reference Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return _VarHandle(self, name)

    def find_var(self, name: str):
        """Walk up the parent chain (reference Scope::FindVar); None if absent."""
        s = self
        while s is not None:
            if name in s._vars:
                return _VarHandle(s, name)
            s = s._parent
        return None

    def erase(self, names):
        for n in names if isinstance(names, (list, tuple)) else [names]:
            self._vars.pop(n, None)

    def local_var_names(self):
        return sorted(self._vars)

    def set(self, name, value):
        self._vars[name] = value

    def get(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        raise NotFoundError(f"variable {name!r} not found in scope chain")

    # ------------------------------------------------------------ hierarchy
    def new_scope(self) -> "Scope":
        kid = Scope(parent=self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        for k in self._kids:
            k.drop_kids()
        self._kids.clear()

    def parent(self):
        return self._parent


class _VarHandle:
    """A named slot in a scope (reference framework::Variable): typed get/set."""

    __slots__ = ("_scope", "_name")

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    @property
    def name(self):
        return self._name

    def get_tensor(self):
        return self._scope._vars.get(self._name)

    def set_tensor(self, value):
        self._scope._vars[self._name] = value

    set_value = set_tensor

    def is_initialized(self):
        return self._scope._vars.get(self._name) is not None


_global = Scope()
_scope_stack = [_global]


def global_scope() -> Scope:
    """The current scope: the root, or the innermost active scope_guard."""
    return _scope_stack[-1]


class scope_guard:
    """reference: paddle.static.scope_guard context manager."""

    def __init__(self, scope: Scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scope_stack.pop()
        return False
