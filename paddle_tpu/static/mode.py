"""Dynamic/static mode switch (reference: paddle.enable_static/disable_static)."""
from __future__ import annotations

_STATIC_MODE = False


def enable_static():
    global _STATIC_MODE
    _STATIC_MODE = True


def disable_static():
    global _STATIC_MODE
    _STATIC_MODE = False


def in_static_mode() -> bool:
    return _STATIC_MODE
