"""Program IR.

Reference analog: ProgramDesc/BlockDesc/OpDesc (`paddle/fluid/framework/
framework.proto`, program_desc.h:31) + python mirrors (fluid/framework.py:4834).

TPU-native design: the Program is a *build-time op tape*. In static mode every
framework op (the same `primitive_call` the eager mode uses) appends an Operator
carrying the pure-jax lowering closure + op_role (survey App. A), and outputs
become symbolic Variables (jax.eval_shape avals). Lowering a Program to XLA is
then trivial: replay the tape over tracers inside one jit — the IPU
"whole program → one compiled computation" model (survey §3.5), with no
per-op kernel registry because each Operator carries its own lowering.
"""
from __future__ import annotations

import collections
import contextlib

import jax
import numpy as np

from ..core import dispatch as dispatch_mod
from ..core.dtype import convert_dtype, to_jax_dtype
from ..core.tensor import Tensor
from ..utils.misc import unique_name


class OpRole:
    """reference: paddle/fluid/framework/op_proto_maker.h:25"""

    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 4
    Dist = 8
    LRSched = 16
    Loss = 0x100


class Variable(Tensor):
    """Symbolic tensor: _value is a jax.ShapeDtypeStruct (aval)."""

    def __init__(self, shape, dtype, name=None, block=None, is_data=False,
                 stop_gradient=True):
        aval = jax.ShapeDtypeStruct(tuple(int(s) if s != -1 else 1 for s in shape),
                                    to_jax_dtype(dtype))
        Tensor.__init__(self, np.zeros((), np.float32), stop_gradient=stop_gradient)
        self._value = aval
        self.name = name or unique_name.generate("var")
        self.block = block
        self.is_data = is_data
        self.desc_shape = list(shape)

    @property
    def shape(self):
        return list(self.desc_shape)

    def numpy(self):
        raise RuntimeError(
            f"Variable {self.name!r} has no value at build time; run the program "
            "through an Executor first"
        )

    def detach(self):
        # static graph: grads flow only into captured params, so detach is identity
        return self

    def clone(self):
        return self

    def __repr__(self):
        return f"Variable(name={self.name}, shape={self.desc_shape}, dtype={self.dtype})"


class Operator:
    """One recorded op: type name, the pure-jax lowering, inputs, outputs, attrs."""

    __slots__ = ("type", "fn", "inputs", "outputs", "attrs", "op_role")

    def __init__(self, type, fn, inputs, outputs, attrs=None, op_role=OpRole.Forward):
        self.type = type
        self.fn = fn  # pure jax callable over input arrays
        self.inputs = inputs  # list of Tensor/Variable (or nested lists)
        self.outputs = outputs  # list of Variable
        self.attrs = attrs or {}
        self.op_role = op_role

    def __repr__(self):
        return f"{self.type}({[getattr(i, 'name', '?') for i in self.inputs]}) -> " \
               f"{[o.name for o in self.outputs]}"


class Block:
    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.ops: list[Operator] = []
        self.vars: dict[str, Variable] = collections.OrderedDict()
        # tape version, bumped by every PassBase.apply. Lives on the BLOCK
        # (shared by Program.clone aliases), not the Program wrapper: a pass
        # applied through one alias must invalidate executors holding any
        # alias. The Executor keys its compiled cache on the global block's
        # value.
        self._version = 0

    def var(self, name):
        return self.vars[name]

    def has_var(self, name):
        return name in self.vars

    def append_op(self, op: Operator):
        self.ops.append(op)
        return op

    def create_var(self, shape, dtype, name=None, **kw):
        v = Variable(shape, dtype, name, block=self, **kw)
        self.vars[v.name] = v
        return v


class Program:
    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._data_vars: list[Variable] = []
        self._minimize_spec = None  # (optimizer, loss_var)
        self.random_seed = 0

    @property
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def all_ops(self):
        return [op for b in self.blocks for op in b.ops]

    def list_vars(self):
        return list(self.global_block.vars.values())

    def clone(self, for_test=False):
        import copy

        new = Program.__new__(Program)
        new.blocks = self.blocks  # share the tape (reference clones share descs)
        new.current_block_idx = self.current_block_idx
        new._data_vars = list(self._data_vars)
        new._minimize_spec = None if for_test else self._minimize_spec
        new.random_seed = self.random_seed
        return new

    # ------------------------------------------------------------ param capture
    def captured_params(self):
        """Concrete Tensors referenced by ops (weights) in deterministic order."""
        seen, out = set(), []
        for op in self.all_ops():
            for t in _flat_inputs(op.inputs):
                if isinstance(t, Tensor) and not isinstance(t, Variable):
                    if id(t) not in seen:
                        seen.add(id(t))
                        out.append(t)
        return out

    def __repr__(self):
        lines = [f"Program(blocks={len(self.blocks)}, ops={len(self.all_ops())})"]
        for op in self.all_ops()[:50]:
            lines.append("  " + repr(op))
        return "\n".join(lines)


def _flat_inputs(inputs):
    for i in inputs:
        if isinstance(i, (list, tuple)):
            yield from _flat_inputs(i)
        else:
            yield i


# --------------------------------------------------------------- build context
_default_main = Program()
_default_startup = Program()
_current_role = [OpRole.Forward]


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    prev_m, prev_s = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev_m, prev_s


@contextlib.contextmanager
def name_scope(prefix):
    yield


@contextlib.contextmanager
def op_role_guard(role):
    _current_role.append(role)
    try:
        yield
    finally:
        _current_role.pop()


_current_device: list = [None]

# fp16_guard scope stack (reference: fp16_utils.py _fp16_guard_pattern —
# there a name_scope marker on op_namescope; here a direct op attr). Ops
# recorded while the top is truthy carry attrs["in_fp16_guard"], which the
# pure-fp16 pass consults when use_fp16_guard is on.
_current_fp16_guard: list = [False]


@contextlib.contextmanager
def fp16_guard_scope():
    _current_fp16_guard.append(True)
    try:
        yield
    finally:
        _current_fp16_guard.pop()


@contextlib.contextmanager
def device_guard(device=None):
    """reference: paddle.static.device_guard — ops recorded inside carry a
    device/stage annotation (`op.attrs['device']`) that the static pipeline
    splitter (static/pipeline.py, the PipelineOptimizer analog at
    fluid/optimizer.py:4323) uses to cut stage boundaries."""
    _current_device.append(device)
    try:
        yield
    finally:
        _current_device.pop()


def data(name, shape, dtype="float32", lod_level=0):
    """reference: paddle.static.data — declares a feed Variable."""
    prog = default_main_program()
    shape = [1 if s in (-1, None) else s for s in shape]
    v = prog.global_block.create_var(shape, dtype, name=name, is_data=True)
    prog._data_vars.append(v)
    return v


# --------------------------------------------------------------- static tracer
def _static_record(fn, args, name, attrs=None):
    """Called from core.dispatch when static mode is active: append an Operator."""
    prog = default_main_program()
    block = prog.current_block()

    avals = []
    for a in args:
        avals.append(_to_aval(a))
    out_aval = jax.eval_shape(fn, *avals)
    is_tuple = isinstance(out_aval, (tuple, list))
    outs_avals = list(out_aval) if is_tuple else [out_aval]
    outputs = [
        block.create_var(o.shape, convert_dtype(o.dtype),
                         name=unique_name.generate(name or "op"))
        for o in outs_avals
    ]
    op = Operator(name or getattr(fn, "__name__", "op"), fn, list(args), outputs,
                  op_role=_current_role[-1])
    if attrs:
        op.attrs.update(attrs)
    if _current_device[-1] is not None:
        op.attrs["device"] = _current_device[-1]
    if _current_fp16_guard[-1]:
        op.attrs["in_fp16_guard"] = True
    block.append_op(op)
    if is_tuple:
        return tuple(outputs)
    return outputs[0]


def _to_aval(a):
    if isinstance(a, Variable):
        return a._value
    if isinstance(a, Tensor):
        return jax.ShapeDtypeStruct(tuple(a._value.shape), a._value.dtype)
    if isinstance(a, (list, tuple)):
        return type(a)(_to_aval(x) for x in a)
    return a


def _static_active(args) -> bool:
    from .mode import in_static_mode

    if not in_static_mode():
        return False
    return True


dispatch_mod._static_hook = (_static_active, _static_record)


# The hierarchical runtime Scope lives in static/scope.py
# (reference: paddle/fluid/framework/scope.h:78).
from .scope import Scope, global_scope, scope_guard  # noqa: E402,F401
