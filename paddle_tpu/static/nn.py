"""paddle.static.nn (reference: python/paddle/static/nn/) — static-graph layer
helpers. Because static mode records through the same op dispatch, these simply
instantiate the dygraph layers and call them."""
from __future__ import annotations

from .. import nn as dynn


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_dim = 1
    for s in x.shape[num_flatten_dims:]:
        in_dim *= int(s)
    if len(x.shape) > num_flatten_dims + 1:
        x = x.flatten(num_flatten_dims)
    layer = dynn.Linear(in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr)
    out = layer(x)
    if activation:
        out = getattr(dynn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    in_c = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    layer = dynn.Conv2D(in_c, num_filters, filter_size, stride, padding, dilation,
                        groups, weight_attr=param_attr, bias_attr=bias_attr,
                        data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-05, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None, **kw):
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    layer = dynn.BatchNorm2D(c, momentum=momentum, epsilon=epsilon,
                             weight_attr=param_attr, bias_attr=bias_attr)
    layer.training = not is_test
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def embedding(input, size, is_sparse=False, param_attr=None, dtype="float32"):
    layer = dynn.Embedding(size[0], size[1], weight_attr=param_attr)
    return layer(input)


from .control_flow import case, cond, switch_case, while_loop  # noqa: F401,E402


# ===================================================================== parity
# batch (reference: python/paddle/static/nn/__init__.py __all__). Dygraph
# layers carry the math; sequence_* ops follow this framework's documented
# dynamic-shape policy (SURVEY hard-part #2): a "sequence batch" is a padded
# dense [B, T, ...] tensor plus per-row `length` — the (LoDTensor -> padded +
# lengths) translation the reference performs in sequence_pad.
import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive_call
from ..core.tensor import Tensor


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _len_mask(x, length, time_axis=1, ndim=None):
    """[B, T] validity mask; with ndim, right-padded with singleton dims so
    it broadcasts against [B, T, ...]."""
    T = x.shape[time_axis]
    if length is None:
        m = jnp.ones(tuple(int(s) for s in x.shape[:2]), bool)
    else:
        L = _val(length).reshape(-1)
        m = jnp.arange(T)[None, :] < L[:, None]
    if ndim is not None:
        m = m.reshape(m.shape + (1,) * (ndim - 2))
    return m


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCHW"):
    layer = dynn.Conv2DTranspose(int(input.shape[1]), num_filters,
                                 filter_size, stride=stride, padding=padding,
                                 dilation=dilation, groups=groups,
                                 weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(input)
    return getattr(dynn.functional, act)(out) if act else out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCDHW"):
    layer = dynn.Conv3D(int(input.shape[1]), num_filters, filter_size,
                        stride=stride, padding=padding, dilation=dilation,
                        groups=groups, weight_attr=param_attr,
                        bias_attr=bias_attr)
    out = layer(input)
    return getattr(dynn.functional, act)(out) if act else out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCDHW"):
    layer = dynn.Conv3DTranspose(int(input.shape[1]), num_filters,
                                 filter_size, stride=stride, padding=padding,
                                 dilation=dilation, groups=groups,
                                 weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(input)
    return getattr(dynn.functional, act)(out) if act else out


def group_norm(input, groups, epsilon=1e-05, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    layer = dynn.GroupNorm(groups, int(input.shape[1]), epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(input)
    return getattr(dynn.functional, act)(out) if act else out


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    nd = len(input.shape)
    cls = {3: dynn.InstanceNorm1D, 4: dynn.InstanceNorm2D,
           5: dynn.InstanceNorm3D}[nd]
    return cls(int(input.shape[1]), epsilon=epsilon, weight_attr=param_attr,
               bias_attr=bias_attr)(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = dynn.LayerNorm(shape, epsilon=epsilon,
                           weight_attr=param_attr if scale else False,
                           bias_attr=bias_attr if shift else False)
    out = layer(input)
    return getattr(dynn.functional, act)(out) if act else out


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    n = {"all": 1, "channel": int(x.shape[1]), "element":
         int(np.prod(x.shape[1:]))}[mode]
    layer = dynn.PReLU(num_parameters=n, weight_attr=param_attr)
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Power-iteration spectral normalization of a weight tensor (reference
    spectral_norm op) — returns w / sigma_max."""
    def f(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), w.dtype) / np.sqrt(wm.shape[0])
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / (sigma + eps)

    return primitive_call(f, weight, name="spectral_norm")


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay=0.9999999, sync_stats=False,
              enable_scale_and_shift=False):
    """Normalization by accumulated batch statistics without learnable
    affine (reference data_norm_op — the CTR-model normalizer)."""
    def f(a):
        mean = jnp.mean(a, axis=0, keepdims=True)
        var = jnp.var(a, axis=0, keepdims=True)
        return (a - mean) / jnp.sqrt(var + epsilon)

    out = primitive_call(f, input, name="data_norm")
    return getattr(dynn.functional, act)(out) if act else out


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    layer = dynn.Bilinear(int(x.shape[-1]), int(y.shape[-1]), size,
                          weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(x, y)
    return getattr(dynn.functional, act)(out) if act else out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference row_conv_op: DeepSpeech2's
    causal-future smoothing): out[t] = sum_{i=0..k} w[i] * x[t+i]."""
    k = int(future_context_size)
    d = int(input.shape[-1])
    from .extras import create_parameter

    w = create_parameter([k + 1, d], "float32", attr=param_attr)

    def f(a, wv):
        # a: [B, T, D]; pad future, window-sum
        pad = jnp.pad(a, ((0, 0), (0, k), (0, 0)))
        out = jnp.zeros_like(a)
        for i in range(k + 1):
            out = out + pad[:, i:i + a.shape[1]] * wv[i]
        return out

    out = primitive_call(f, input, w, name="row_conv")
    return getattr(dynn.functional, act)(out) if act else out


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import deform_conv2d as _dc
    from .extras import create_parameter

    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = create_parameter(
        [num_filters, int(x.shape[1]) // groups, k[0], k[1]], "float32",
        attr=param_attr)
    b = None if bias_attr is False else create_parameter(
        [num_filters], "float32", attr=bias_attr, is_bias=True)
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def crf_decoding(input, param_attr=None, length=None, label=None,
                 transition=None):
    """Viterbi decode over emission scores (reference crf_decoding_op).
    `transition` may be passed directly; otherwise a parameter is created."""
    from ..text.viterbi_decode import viterbi_decode
    from .extras import create_parameter

    n_tags = int(input.shape[-1])
    trans = transition if transition is not None else create_parameter(
        [n_tags + 2, n_tags], "float32", attr=param_attr)
    tv = _val(trans)
    if tv.shape[0] == n_tags + 2:  # strip start/stop rows (linear-chain CRF)
        tv = tv[2:]
    if length is None:
        B, T = input.shape[0], input.shape[1]
        length = Tensor(jnp.full((B,), T, jnp.int64))
    _, path = viterbi_decode(input, Tensor(tv), length,
                             include_bos_eos_tag=False)
    return path


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=10, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nce_op): logistic loss on
    the true class vs `num_neg_samples` uniformly drawn noise classes."""
    from ..core.rng import next_rng_key
    from .extras import create_parameter

    d = int(input.shape[-1])
    w = create_parameter([num_total_classes, d], "float32", attr=param_attr)
    b = None if bias_attr is False else create_parameter(
        [num_total_classes], "float32", attr=bias_attr, is_bias=True)
    key = next_rng_key()

    def f(x, y, wv, *bv):
        B = x.shape[0]
        yv = y.reshape(-1).astype(jnp.int32)
        neg = jax.random.randint(key, (B, num_neg_samples), 0,
                                 num_total_classes)
        pos_logit = jnp.sum(x * wv[yv], axis=-1)
        neg_logit = jnp.einsum("bd,bnd->bn", x, wv[neg])
        if bv:
            pos_logit = pos_logit + bv[0][yv]
            neg_logit = neg_logit + bv[0][neg]
        # logistic: true class -> label 1, noise -> 0
        pos_loss = jnp.log1p(jnp.exp(-pos_logit))
        neg_loss = jnp.sum(jnp.log1p(jnp.exp(neg_logit)), axis=-1)
        return (pos_loss + neg_loss)[:, None]

    args = [input, label, w] + ([b] if b is not None else [])
    return primitive_call(f, *args, name="nce")


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """Large-scale PS embedding lookup (reference sparse_embedding — the
    the_one_ps distributed table). Single-process form: an Embedding whose
    gradient stays row-sparse (SelectedRows) so the PS/SSD tables can ingest
    it; `entry` carries the admission policy."""
    layer = dynn.Embedding(size[0], size[1], padding_idx=padding_idx,
                           sparse=True, weight_attr=param_attr)
    layer.weight.entry = entry
    return layer(input)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference multi_box_head): per-feature-map conv
    predictors for location + confidence, plus prior boxes."""
    from ..vision.ops import prior_box

    n = len(inputs)
    if min_sizes is None:
        # reference ratio schedule
        min_ratio, max_ratio = int(min_ratio), int(max_ratio)
        step = int((max_ratio - min_ratio) / (n - 2)) if n > 2 else 0
        min_sizes, max_sizes = [], []
        for r in range(min_ratio, max_ratio + 1, step or 1):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + (step or 1)) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n - 1]

    locs, confs, boxes, vars_ = [], [], [], []
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        box, var = prior_box(
            feat, image, min_sizes=[min_sizes[i]],
            max_sizes=[max_sizes[i]] if max_sizes else None,
            aspect_ratios=ar, variance=list(variance), flip=flip, clip=clip,
            steps=[steps[i], steps[i]] if steps else [0.0, 0.0],
            offset=offset)
        num_priors = int(np.prod(box.shape[:-1])) // (
            int(feat.shape[2]) * int(feat.shape[3]))
        loc = conv2d(feat, num_priors * 4, kernel_size, padding=pad,
                     stride=stride)
        conf = conv2d(feat, num_priors * num_classes, kernel_size,
                      padding=pad, stride=stride)
        B = int(feat.shape[0])
        locs.append(loc.transpose([0, 2, 3, 1]).reshape([B, -1, 4]))
        confs.append(conf.transpose([0, 2, 3, 1]).reshape(
            [B, -1, num_classes]))
        boxes.append(Tensor(_val(box).reshape(-1, 4)))
        vars_.append(Tensor(_val(var).reshape(-1, 4)))
    from ..tensor_ops.manipulation import concat

    return (concat(locs, axis=1), concat(confs, axis=1),
            concat(boxes, axis=0), concat(vars_, axis=0))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    from .extras import py_func as _pf

    return _pf(func, x, out, backward_func=backward_func)


# --------------------------------------------------------------- sequence ops
def sequence_pad(x, pad_value, maxlen=None, name=None):
    """List-of-rows -> (padded [B, T, ...], lengths [B]) (reference
    sequence_pad_op). Accepts a python list of arrays (the LoD analog)."""
    if isinstance(x, Tensor):
        xv, lens = x, x.shape[1]
        if maxlen is not None and maxlen < x.shape[1]:
            xv, lens = Tensor(x._value[:, :maxlen]), maxlen
        elif maxlen is not None and maxlen > x.shape[1]:
            pv = float(pad_value if not isinstance(pad_value, Tensor)
                       else np.asarray(pad_value._value))
            pads = [(0, 0), (0, maxlen - x.shape[1])] + \
                [(0, 0)] * (len(x.shape) - 2)
            xv = Tensor(jnp.pad(x._value, pads, constant_values=pv))
        return xv, Tensor(jnp.full((x.shape[0],), lens, jnp.int64))
    seqs = [_val(s) for s in x]
    T = maxlen if maxlen is not None else max(s.shape[0] for s in seqs)
    pv = float(pad_value if not isinstance(pad_value, Tensor)
               else np.asarray(pad_value._value))
    # a shorter maxlen TRUNCATES, and the returned lengths agree with what
    # survived (same contract as core/ragged.LoDTensor.to_padded)
    seqs = [s[:T] for s in seqs]
    out = jnp.stack([
        jnp.pad(s, [(0, T - s.shape[0])] + [(0, 0)] * (s.ndim - 1),
                constant_values=pv) for s in seqs])
    lens = jnp.asarray([s.shape[0] for s in seqs], jnp.int64)
    return Tensor(out), Tensor(lens)


def sequence_unpad(x, length, name=None):
    """(padded, lengths) -> list of per-row arrays (host-side: row shapes are
    data-dependent, the same reason the reference keeps LoD on CPU)."""
    xv = np.asarray(_val(x))
    L = np.asarray(_val(length)).reshape(-1)
    return [Tensor(jnp.asarray(xv[i, :int(L[i])])) for i in range(len(L))]


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  length=None):
    """Masked pool over the time dim (reference sequence_pool_op)."""
    def f(a):
        mask = _len_mask(a, length, ndim=a.ndim)
        m = mask.astype(a.dtype)
        pt = pool_type.lower()
        if pt == "sum":
            return jnp.sum(a * m, axis=1)
        if pt == "average":
            return jnp.sum(a * m, axis=1) / jnp.maximum(
                jnp.sum(m, axis=1), 1.0)
        if pt == "sqrt":
            return jnp.sum(a * m, axis=1) / jnp.sqrt(jnp.maximum(
                jnp.sum(m, axis=1), 1.0))
        if pt == "max":
            return jnp.max(jnp.where(mask, a, -1e30), axis=1)
        if pt == "first":
            return a[:, 0]
        if pt == "last":
            if length is None:
                return a[:, -1]
            L = _val(length).reshape(-1).astype(jnp.int32)
            return a[jnp.arange(a.shape[0]), jnp.maximum(L - 1, 0)]
        raise ValueError(f"unsupported pool_type {pool_type}")

    return primitive_call(f, input, name="sequence_pool")


def sequence_softmax(input, use_cudnn=False, name=None, length=None):
    def f(a):
        mask = _len_mask(a, length, ndim=a.ndim)
        z = jnp.where(mask, a, -1e30)
        return jnp.where(mask, jax.nn.softmax(z, axis=1), 0.0)

    return primitive_call(f, input, name="sequence_softmax")


def sequence_reverse(x, name=None, length=None):
    """Reverse each row over its valid prefix (reference sequence_reverse)."""
    def f(a):
        T = a.shape[1]
        if length is None:
            return a[:, ::-1]
        L = _val(length).reshape(-1).astype(jnp.int32)
        idx = jnp.arange(T)[None, :]
        rev = jnp.where(idx < L[:, None], L[:, None] - 1 - idx, idx)
        return jnp.take_along_axis(
            a, rev.reshape(rev.shape + (1,) * (a.ndim - 2)), axis=1)

    return primitive_call(f, x, name="sequence_reverse")


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length=length)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window conv over time (reference sequence_conv_op): each step
    sees `filter_size` neighboring steps centered per padding_start."""
    d = int(input.shape[-1])
    from .extras import create_parameter

    w = create_parameter([filter_size * d, num_filters], "float32",
                         attr=param_attr)
    b = None if bias_attr is False else create_parameter(
        [num_filters], "float32", attr=bias_attr, is_bias=True)
    start = -int((filter_size - 1) // 2) if padding_start is None \
        else int(padding_start)

    def f(a, wv, *bv):
        B, T, D = a.shape
        cols = []
        for i in range(filter_size):
            off = start + i
            if off < 0:
                sl = jnp.pad(a[:, :T + off], ((0, 0), (-off, 0), (0, 0)))
            elif off > 0:
                sl = jnp.pad(a[:, off:], ((0, 0), (0, off), (0, 0)))
            else:
                sl = a
            cols.append(sl)
        col = jnp.concatenate(cols, axis=-1)  # [B, T, k*D]
        out = col @ wv
        if bv:
            out = out + bv[0]
        return out

    out = primitive_call(f, input, w, *([b] if b is not None else []),
                         name="sequence_conv")
    return getattr(dynn.functional, act)(out) if act else out


def sequence_concat(input, name=None):
    """Concat sequences row-wise along time (reference sequence_concat)."""
    from ..tensor_ops.manipulation import concat

    return concat(list(input), axis=1)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat each row of x to match y's batch grouping. Padded-batch form:
    x [B, ...], y [B*r, ...] -> tile x rows r times (uniform expansion)."""
    def f(a, b):
        r = b.shape[0] // a.shape[0]
        return jnp.repeat(a, r, axis=0)

    return primitive_call(f, x, y, name="sequence_expand")


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_reshape(input, new_dim):
    """Reshape the feature dim, redistributing time steps (reference
    sequence_reshape_op)."""
    def f(a):
        B = a.shape[0]
        return a.reshape(B, -1, new_dim)

    return primitive_call(f, input, name="sequence_reshape")


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """All win_size-grams per row (reference sequence_enumerate_op)."""
    def f(a):
        T = a.shape[1]
        cols = []
        for i in range(win_size):
            sl = a[:, i:]
            pad = [(0, 0), (0, i)] + [(0, 0)] * (a.ndim - 2)
            cols.append(jnp.pad(sl, pad, constant_values=pad_value))
        return jnp.stack(cols, axis=-1)

    return primitive_call(f, input, name="sequence_enumerate")


def sequence_slice(input, offset, length, name=None):
    """Per-row slice [offset, offset+length) over time (reference
    sequence_slice_op). `length` must be uniform (static shapes)."""
    def f(a, off, ln):
        if isinstance(ln, jax.core.Tracer):
            raise ValueError("sequence_slice needs concrete lengths "
                             "(static output shapes)")
        l0 = int(np.asarray(ln).reshape(-1)[0])
        offs = off.reshape(-1).astype(jnp.int32)
        rows = [jax.lax.dynamic_slice_in_dim(a[i], offs[i], l0, axis=0)
                for i in range(a.shape[0])]
        return jnp.stack(rows)

    return primitive_call(f, input, offset, length, name="sequence_slice")


def sequence_scatter(input, index, updates, name=None):
    """Scatter updates into per-row time positions (reference
    sequence_scatter_op)."""
    def f(a, idx, upd):
        B = a.shape[0]
        rows = jnp.repeat(jnp.arange(B)[:, None], idx.shape[1], axis=1)
        return a.at[rows, idx.astype(jnp.int32)].add(upd)

    return primitive_call(f, input, index, updates, name="sequence_scatter")
