"""paddle.static.nn (reference: python/paddle/static/nn/) — static-graph layer
helpers. Because static mode records through the same op dispatch, these simply
instantiate the dygraph layers and call them."""
from __future__ import annotations

from .. import nn as dynn


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_dim = 1
    for s in x.shape[num_flatten_dims:]:
        in_dim *= int(s)
    if len(x.shape) > num_flatten_dims + 1:
        x = x.flatten(num_flatten_dims)
    layer = dynn.Linear(in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr)
    out = layer(x)
    if activation:
        out = getattr(dynn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    in_c = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    layer = dynn.Conv2D(in_c, num_filters, filter_size, stride, padding, dilation,
                        groups, weight_attr=param_attr, bias_attr=bias_attr,
                        data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-05, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None, **kw):
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    layer = dynn.BatchNorm2D(c, momentum=momentum, epsilon=epsilon,
                             weight_attr=param_attr, bias_attr=bias_attr)
    layer.training = not is_test
    out = layer(input)
    if act:
        out = getattr(dynn.functional, act)(out)
    return out


def embedding(input, size, is_sparse=False, param_attr=None, dtype="float32"):
    layer = dynn.Embedding(size[0], size[1], weight_attr=param_attr)
    return layer(input)


from .control_flow import case, cond, switch_case, while_loop  # noqa: F401,E402
