"""Static-graph parity batch (reference: python/paddle/static/__init__.py —
append_backward/gradients, program-state and serialization helpers, EMA,
strategy/compiled-program shells, Print, py_func, IPU-strategy analogs).

Gradient design: the executor compiles the WHOLE program into one XLA
computation (survey §3.5), so grad "ops" are not appended as tape entries the
way fluid's append_backward splices grad blocks. Instead `append_backward` /
`gradients` register GradVariable fetches; the executor differentiates the
replayed program with jax.grad when such a fetch is requested — same user
contract (fetch `x@GRAD`), XLA-native mechanics.
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import ParamAttr
from .program import Program, Variable, default_main_program

__all__ = [
    "append_backward", "gradients", "GradVariable", "py_func", "Print",
    "create_global_var", "create_parameter", "ExponentialMovingAverage",
    "BuildStrategy", "ExecutionStrategy", "ParallelExecutor",
    "WeightNormParamAttr", "accuracy", "auc", "save", "load", "save_to_file",
    "load_from_file", "serialize_persistables", "deserialize_persistables",
    "deserialize_program", "normalize_program", "load_program_state",
    "set_program_state", "IpuStrategy", "IpuCompiledProgram",
    "ipu_shard_guard", "set_ipu_shard", "npu_places", "mlu_places",
]


class GradVariable(Variable):
    """d(target)/d(wrt) as a fetchable symbolic var (named `wrt@GRAD`)."""

    def __init__(self, target: Variable, wrt: Variable):
        super().__init__(wrt.shape, "float32", name=f"{wrt.name}@GRAD")
        self.target = target
        self.wrt = wrt


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Register grads of `loss` for every trainable parameter; returns
    [(param, grad_var), ...] like the reference (fluid/backward.py:1376)."""
    prog = loss.block.program if getattr(loss, "block", None) else \
        default_main_program()
    params = parameter_list if parameter_list is not None else [
        p for p in prog.captured_params() if not p.stop_gradient]
    no_grad = set(id(v) for v in (no_grad_set or []))
    pairs = []
    for p in params:
        if id(p) in no_grad:
            continue
        gv = GradVariable(loss, p)
        prog._grad_vars = getattr(prog, "_grad_vars", {})
        prog._grad_vars[gv.name] = gv
        pairs.append((p, gv))
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Grad vars of sum(targets) w.r.t. each input (reference
    paddle.static.gradients). Fetch them through Executor.run."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) != 1:
        # multiple targets sum their cotangents; represent as a fresh sum var
        raise NotImplementedError("multiple targets: pass their sum instead")
    out = []
    prog = default_main_program()
    for x in inputs:
        gv = GradVariable(targets[0], x)
        prog._grad_vars = getattr(prog, "_grad_vars", {})
        prog._grad_vars[gv.name] = gv
        out.append(gv)
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op inside the compiled program (reference py_func_op).

    TPU-native: jax.pure_callback — the XLA program calls back into the host
    at this point; `out` declares the result aval(s). With backward_func, a
    custom VJP routes cotangents through another callback."""
    from ..core.dispatch import primitive_call

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype
                                   if hasattr(o._value, "dtype")
                                   else jnp.float32) for o in outs]

    def host(*arrays):
        res = func(*[np.asarray(a) for a in arrays])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r) for r in res)

    single = len(shapes) == 1

    if backward_func is None:
        def f(*arrays):
            res = jax.pure_callback(host, tuple(shapes), *arrays)
            return res[0] if single else res

        return primitive_call(f, *xs, name="py_func")

    @jax.custom_vjp
    def callback_op(*arrays):
        res = jax.pure_callback(host, tuple(shapes), *arrays)
        return res[0] if single else res

    def fwd(*arrays):
        return callback_op(*arrays), arrays

    def bwd(arrays, g):
        gs = (g,) if single else tuple(g)
        in_shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays]

        def host_bwd(*args):
            n = len(arrays)
            res = backward_func(*[np.asarray(v) for v in args])
            res = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r) for r in res)

        return jax.pure_callback(host_bwd, tuple(in_shapes), *arrays, *gs)

    callback_op.defvjp(fwd, bwd)

    def f(*arrays):
        return callback_op(*arrays)

    return primitive_call(f, *xs, name="py_func")


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug-print a tensor during execution (reference print_op) via
    jax.debug.print — works inside the compiled program."""
    from ..core.dispatch import primitive_call

    msg = message or ""
    name = getattr(input, "name", "tensor")

    def f(a):
        jax.debug.print(msg + " {name}: {val}", name=name, val=a)
        return a

    return primitive_call(f, input, name="print")


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A non-trainable program-scope variable with an initial value
    (reference layers/tensor.py create_global_var)."""
    from ..core.dtype import to_jax_dtype

    t = Tensor(jnp.full(tuple(int(s) for s in shape), value,
                        to_jax_dtype(dtype)), stop_gradient=True)
    t.name = name or "global_var"
    t.persistable = persistable
    prog = default_main_program()
    prog._global_vars = getattr(prog, "_global_vars", {})
    prog._global_vars[t.name] = t
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..tensor_ops.creation import create_parameter as _cp

    return _cp(shape, dtype=dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference static/ema.py): update() after
    each optimizer step; apply()/restore() swap shadow weights for eval."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._shadow: dict[int, object] = {}
        self._backup: dict[int, object] | None = None
        self._params: list = []
        self._step = 0

    def _ensure(self, params):
        for p in params:
            if id(p) not in self._shadow:
                self._params.append(p)
                self._shadow[id(p)] = p._value

    def update(self, parameters=None):
        from .program import default_main_program

        params = parameters or [p for p in
                                default_main_program().captured_params()
                                if not p.stop_gradient]
        self._ensure(params)
        self._step += 1
        # reference ema (fluid/optimizer.py:4232): the (1+t)/(10+t) warm-up
        # ramp applies ONLY when thres_steps is given, using ITS value — a
        # user's constant decay must stay constant from step 1
        if self._thres_steps is None:
            d = self._decay
        else:
            t = self._thres_steps() if callable(self._thres_steps) \
                else self._thres_steps
            d = min(self._decay, (float(t) + 1.0) / (float(t) + 10.0))
        for p in self._params:
            self._shadow[id(p)] = d * self._shadow[id(p)] + (1 - d) * p._value

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._value for p in self._params}
        for p in self._params:
            p._value = self._shadow[id(p)]
        return self

    def restore(self, executor=None):
        if self._backup:
            for p in self._params:
                p._value = self._backup[id(p)]
            self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.restore()


class BuildStrategy:
    """Graph-build knobs (reference BuildStrategy). XLA owns fusion and
    scheduling on TPU, so these are accepted-and-recorded only; the compiled
    result is already whole-graph optimized."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        # program-level pattern fusion (static/passes.py); CompiledProgram
        # applies the matching registered pass when set (reference
        # build_strategy.fuse_gemm_epilogue -> fuse_gemm_epilogue_pass.cc)
        self.fuse_gemm_epilogue = False
        self.fuse_attention = False
        self.fuse_feedforward = False
        self.memory_optimize = True
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.build_cinn_pass = False


class ExecutionStrategy:
    """Executor knobs (reference ExecutionStrategy); single-stream XLA
    execution makes thread counts moot — recorded for API compat."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.use_thread_pool = False


class ParallelExecutor:
    """reference: fluid/parallel_executor.py — multi-device replicated
    execution. On TPU this is GSPMD: wrap the program in CompiledProgram and
    run through the ordinary Executor (data parallelism comes from sharding
    the feed, not from executor replication)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        from .executor import Executor
        from .program import default_main_program

        self._program = main_program or default_main_program()
        self._exe = Executor()
        self._loss_name = loss_name

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list, return_numpy=return_numpy)


class WeightNormParamAttr(ParamAttr):
    """Weight-normalized parameter attribute (reference
    WeightNormParamAttr): marks a parameter for w = g * v / ||v||
    reparameterization along `dim`."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate, regularizer=regularizer,
                         trainable=trainable)
        self.dim = dim


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC as a Tensor (reference auc op). Stateless single-batch form;
    streaming AUC lives in paddle.metric.Auc."""
    from ..metric import Auc

    m = Auc(num_thresholds=num_thresholds)
    pred = np.asarray(input._value if isinstance(input, Tensor) else input)
    lab = np.asarray(label._value if isinstance(label, Tensor) else label)
    m.update(pred, lab)
    return Tensor(jnp.asarray(np.float32(m.accumulate())))


# ------------------------------------------------------------- serialization
def serialize_persistables(program=None):
    """Pickle all parameter values of `program` (reference
    serialize_persistables -> bytes)."""
    prog = program or default_main_program()
    state = {p.name: np.asarray(p._value) for p in prog.captured_params()}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    set_program_state(program, state)


# deserialize_program intentionally lives in static/io.py: programs hold
# lowering closures and serialize as compiled StableHLO (save_inference_model),
# not as reloadable op-graph pickles — io.py raises the clear error.
from .io import deserialize_program  # noqa: E402,F401


def normalize_program(program, feed_vars, fetch_vars):
    """Prune to the inference graph (reference normalize_program). The op
    tape keeps only ops reachable from fetch_vars; params stay captured."""
    pruned = program.clone(for_test=True)
    pruned._feed_vars = list(feed_vars)
    pruned._fetch_vars = list(fetch_vars)
    return pruned


def save(program, model_path, protocol=4):
    """program + persistables to `<path>.pdmodel` / `<path>.pdparams`
    (reference static.save). load() reads only the .pdparams side; the
    .pdmodel here is a real ProgramDesc protobuf when every op has a
    pdmodel emitter, else a debug text dump (training programs contain
    ops with no OpDesc mapping — grads/optimizer updates)."""
    with open(model_path + ".pdparams", "wb") as f:
        f.write(serialize_persistables(program))
    from .io import serialize_program

    try:
        blob = serialize_program(program)
    except NotImplementedError:
        # emitter gap (unmapped op, scalar-operand arity) → load() never
        # reads this file, keep the debug dump. Other exception types are
        # real exporter bugs and must stay loud.
        blob = repr(program).encode()
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(blob)


def load(program, model_path, executor=None, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        deserialize_persistables(program, f.read())


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.loads(f.read())


def set_program_state(program, state_dict):
    by_name = {p.name: p for p in program.captured_params()}
    missing = []
    for name, val in state_dict.items():
        p = by_name.get(name)
        if p is None:
            missing.append(name)
            continue
        p._value = jnp.asarray(val)
    if missing:
        import warnings

        warnings.warn(f"set_program_state: no parameter for {missing}")


# ----------------------------------------------------------------- IPU analog
class IpuStrategy:
    """Device-compile strategy (reference ipu_strategy.h:32 — capacity is
    strategy, not constant). On TPU the analogs are mesh shape and
    micro-batching; recorded here and consumed by IpuCompiledProgram."""

    def __init__(self):
        self.num_ipus = 1
        self.is_training = True
        self.micro_batch_size = 1
        self.enable_manual_shard = False
        self._options = {}

    def set_graph_config(self, num_ipus=1, is_training=True,
                         micro_batch_size=1, enable_manual_shard=False):
        self.num_ipus = num_ipus
        self.is_training = is_training
        self.micro_batch_size = micro_batch_size
        self.enable_manual_shard = enable_manual_shard

    def set_options(self, options):
        self._options.update(options)

    def set_pipelining_config(self, enable_pipelining=False,
                              batches_per_step=1, enable_gradient_accumulation=False,
                              accumulation_factor=1):
        self._options.update(dict(
            enable_pipelining=enable_pipelining,
            batches_per_step=batches_per_step,
            enable_gradient_accumulation=enable_gradient_accumulation,
            accumulation_factor=accumulation_factor))

    def set_precision_config(self, enable_fp16=False):
        self._options["enable_fp16"] = enable_fp16


class IpuCompiledProgram:
    """Whole-graph device compile (reference IpuCompiledProgram.compile).
    On TPU every program already compiles whole-graph; this shell carries
    the strategy and returns the program for Executor.run."""

    def __init__(self, program=None, scope=None, ipu_strategy=None):
        self.program = program or default_main_program()
        self.ipu_strategy = ipu_strategy or IpuStrategy()

    def compile(self, feed_list=None, fetch_list=None):
        self.program._ipu_strategy = self.ipu_strategy
        return self.program


_ipu_shard_index = [None]


class _IpuShardGuard:
    def __init__(self, index, stage):
        self._index = index
        self._stage = stage
        self._guard = None

    def __enter__(self):
        from .program import device_guard

        # shard index maps onto the pipeline-stage device annotation the
        # static pipeline splitter consumes (static/pipeline.py)
        stage = self._stage if self._stage is not None else self._index
        self._guard = device_guard(f"tpu:{stage}")
        self._guard.__enter__()
        _ipu_shard_index[0] = self._index
        return self

    def __exit__(self, *a):
        _ipu_shard_index[0] = None
        return self._guard.__exit__(*a)


def ipu_shard_guard(index=-1, stage=-1):
    return _IpuShardGuard(index if index >= 0 else 0,
                          stage if stage >= 0 else None)


def set_ipu_shard(call_func, index=-1, stage=-1):
    """Wrap a layer/function so its ops land on the given shard/stage."""
    def wrapper(*args, **kwargs):
        with ipu_shard_guard(index=index, stage=stage):
            return call_func(*args, **kwargs)

    return wrapper


def npu_places(device_ids=None):
    from . import tpu_places

    return tpu_places(device_ids)


def mlu_places(device_ids=None):
    from . import tpu_places

    return tpu_places(device_ids)
