"""Program-rewrite pass framework.

Reference analog: `paddle/fluid/framework/ir/pass.h:53` (C++ graph passes) +
`python/paddle/distributed/passes/pass_base.py` (PassBase/new_pass/PassManager,
with check/conflict semantics). The reference needs ~150 passes because every
backend transform is a graph rewrite; here XLA owns fusion/scheduling, so
passes exist for PROGRAM-level rewrites XLA cannot do: mixed-precision policy,
fusion annotations the bench/profiler reads, quant export, distributed
transforms. The substrate is the Program op tape: a pass edits `block.ops`
(each Operator carries its own pure-jax lowering, so rewrites compose by
function composition).
"""
from __future__ import annotations

import jax.numpy as jnp

from .program import Operator, Program, Variable, _flat_inputs

_PASS_REGISTRY: dict[str, type] = {}


def register_pass(name):
    """reference: pass_base.py register_pass decorator."""

    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name, attrs=None):
    """reference: pass_base.py new_pass factory."""
    if name not in _PASS_REGISTRY:
        raise ValueError(
            f"unknown pass {name!r}; registered: {sorted(_PASS_REGISTRY)}"
        )
    return _PASS_REGISTRY[name](attrs or {})


class PassContext:
    """reference: pass_base.py PassContext — cross-pass state."""

    def __init__(self):
        self.attrs = {}


class PassBase:
    name = "base"

    def __init__(self, attrs=None):
        self.attrs = dict(attrs or {})

    def check(self, program: Program) -> bool:
        """Applicability check (reference _check_self)."""
        return True

    def apply(self, main_program: Program, startup_program=None, context=None):
        if not self.check(main_program):
            raise RuntimeError(f"pass {self.name} not applicable")
        context = context or PassContext()
        self._apply_impl(main_program, startup_program, context)
        main_program._lowered_cache.clear()
        applied = context.attrs.setdefault("applied_passes", [])
        applied.append(self.name)
        return context

    def _apply_impl(self, main_program, startup_program, context):
        raise NotImplementedError


class PassManager:
    """reference: pass_base.py PassManager — ordered application."""

    def __init__(self, passes):
        self.passes = list(passes)
        self.context = PassContext()

    def apply(self, main_programs, startup_programs=None):
        mains = main_programs if isinstance(main_programs, (list, tuple)) \
            else [main_programs]
        starts = startup_programs or [None] * len(mains)
        for m, s in zip(mains, starts):
            for p in self.passes:
                p.apply(m, s, self.context)
        return self.context

    @property
    def names(self):
        return [p.name for p in self.passes]


def _use_counts(block):
    """How many ops read each Variable (by id) — fusion safety check."""
    counts: dict[int, int] = {}
    for op in block.ops:
        for t in _flat_inputs(op.inputs):
            if isinstance(t, Variable):
                counts[id(t)] = counts.get(id(t), 0) + 1
    return counts


def _cast_wrap(fn, src_dtype, dst_dtype):
    """Wrap an op lowering so floating inputs of `src_dtype` are cast to
    `dst_dtype` before the op runs — the one cast-policy closure shared by
    every mixed-precision pass (static AMP O2, auto_parallel_amp/fp16)."""

    def f(*ins):
        cast = [a.astype(dst_dtype)
                if hasattr(a, "dtype") and a.dtype == src_dtype else a
                for a in ins]
        return fn(*cast)

    return f


# -------------------------------------------------------------------- AMP O2
_AMP_WHITELIST = {
    "matmul", "matmul_v2", "linear", "conv2d", "conv1d", "conv3d", "einsum",
    "mul", "bmm", "addmm", "fused_gemm_epilogue",
}
_AMP_BLACKLIST = {
    "softmax", "log_softmax", "cross_entropy", "exp", "log", "mean",
    "reduce_mean", "sum", "reduce_sum", "layer_norm", "batch_norm",
    "logsumexp", "norm",
}


@register_pass("auto_mixed_precision")
class AMPO2Pass(PassBase):
    """Static AMP at O2 with master weights.

    Reference analog: fluid/contrib/mixed_precision/fp16_utils.py
    cast_model_to_fp16 + the master-weight machinery in the AMP optimizer.
    TPU-native: whitelist ops compute in bfloat16 (MXU-native); the Executor's
    parameter arrays stay fp32 — they ARE the master weights (the optimizer
    updates fp32; weights are cast at each use inside the compiled program,
    which XLA folds into a single cast per buffer per step).
    """

    def _apply_impl(self, main_program, startup_program, context):
        dtype = jnp.bfloat16 if self.attrs.get("dtype", "bfloat16") == \
            "bfloat16" else jnp.float16

        for block in main_program.blocks:
            for op in block.ops:
                if "amp" in op.attrs:
                    continue  # idempotent: the attr records the applied policy
                base = op.type.split("/")[-1]
                if base in _AMP_WHITELIST:
                    op.fn = _cast_wrap(op.fn, jnp.float32, dtype)
                    op.attrs["amp"] = "bf16"
                elif base in _AMP_BLACKLIST:
                    # force fp32 for numerically-sensitive ops
                    op.fn = _cast_wrap(op.fn, dtype, jnp.float32)
                    op.attrs["amp"] = "fp32"
        context.attrs["amp_dtype"] = jnp.dtype(dtype).name


# -------------------------------------------------------- fuse gemm epilogue
_EPILOGUE_ACTS = {"relu", "gelu", "tanh", "sigmoid"}


@register_pass("fuse_gemm_epilogue")
class FuseGemmEpiloguePass(PassBase):
    """Fuse matmul + add(bias) [+ activation] chains into one Operator.

    Reference analog: fuse_gemm_epilogue_pass.cc (cublasLt epilogues). On TPU
    XLA fuses the epilogue into the MXU matmul anyway — the value here is the
    PROGRAM-level annotation (profiler/bench attribution, and one tape node
    instead of three for replay/pass traversal), matching the reference's
    graph-level contract.
    """

    def _apply_impl(self, main_program, startup_program, context):
        n_fused = 0
        for block in main_program.blocks:
            counts = _use_counts(block)
            out_of = {}
            for op in block.ops:
                for o in op.outputs:
                    out_of[id(o)] = op
            ops = block.ops
            i = 0
            new_ops = []
            consumed = set()
            while i < len(ops):
                op = ops[i]
                if id(op) in consumed:
                    i += 1
                    continue
                chain = self._match(ops, i, counts)
                if chain is None:
                    new_ops.append(op)
                    i += 1
                    continue
                mm, add, act = chain
                parts = [mm, add] + ([act] if act else [])
                mm_pos = next(
                    j for j, t in enumerate(add.inputs)
                    if isinstance(t, Variable) and id(t) == id(mm.outputs[0])
                )
                fused_fn = self._compose(mm, add, act, mm_pos)
                fused_inputs = list(mm.inputs) + [
                    t for j, t in enumerate(add.inputs) if j != mm_pos
                ]
                last = parts[-1]
                fused = Operator(
                    "fused_gemm_epilogue", fused_fn, fused_inputs,
                    last.outputs,
                    attrs={"epilogue": (act.type if act else "bias"),
                           "fused_from": [p.type for p in parts]},
                    op_role=mm.op_role,
                )
                new_ops.append(fused)
                for p in parts[1:]:
                    consumed.add(id(p))
                n_fused += 1
                i += 1
            block.ops = [o for o in new_ops]
        context.attrs["fused_gemm_epilogue"] = n_fused

    @staticmethod
    def _match(ops, i, counts):
        op = ops[i]
        if op.type.split("/")[-1] not in ("matmul", "matmul_v2", "mul"):
            return None
        if len(op.outputs) != 1 or counts.get(id(op.outputs[0]), 0) != 1:
            return None
        # the single consumer must be the next-op add with the matmul output
        nxt = next((o for o in ops[i + 1:]
                    if any(isinstance(t, Variable) and id(t) == id(op.outputs[0])
                           for t in _flat_inputs(o.inputs))), None)
        if nxt is None or nxt.type.split("/")[-1] not in ("add", "elementwise_add"):
            return None
        if len(nxt.outputs) != 1:
            return None
        act = None
        if counts.get(id(nxt.outputs[0]), 0) == 1:
            cand = next((o for o in ops
                         if any(isinstance(t, Variable)
                                and id(t) == id(nxt.outputs[0])
                                for t in _flat_inputs(o.inputs))), None)
            if cand is not None and cand.type.split("/")[-1] in _EPILOGUE_ACTS \
                    and len(cand.outputs) == 1:
                act = cand
        return op, nxt, act

    @staticmethod
    def _compose(mm, add, act, mm_pos):
        n_mm = len(mm.inputs)

        def fused(*ins):
            y = mm.fn(*ins[:n_mm])
            add_args = list(ins[n_mm:])
            add_args.insert(mm_pos, y)
            y = add.fn(*add_args)
            if act is not None:
                y = act.fn(y)
            return y

        return fused
