"""Program-rewrite pass framework.

Reference analog: `paddle/fluid/framework/ir/pass.h:53` (C++ graph passes) +
`python/paddle/distributed/passes/pass_base.py` (PassBase/new_pass/PassManager,
with check/conflict semantics). The reference needs ~150 passes because every
backend transform is a graph rewrite; here XLA owns fusion/scheduling, so
passes exist for PROGRAM-level rewrites XLA cannot do: mixed-precision policy,
fusion annotations the bench/profiler reads, quant export, distributed
transforms. The substrate is the Program op tape: a pass edits `block.ops`
(each Operator carries its own pure-jax lowering, so rewrites compose by
function composition).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .program import Operator, Program, Variable, _flat_inputs

_PASS_REGISTRY: dict[str, type] = {}


def register_pass(name):
    """reference: pass_base.py register_pass decorator."""

    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name, attrs=None):
    """reference: pass_base.py new_pass factory."""
    if name not in _PASS_REGISTRY:
        raise ValueError(
            f"unknown pass {name!r}; registered: {sorted(_PASS_REGISTRY)}"
        )
    return _PASS_REGISTRY[name](attrs or {})


class PassContext:
    """reference: pass_base.py PassContext — cross-pass state."""

    def __init__(self):
        self.attrs = {}


class PassBase:
    name = "base"

    def __init__(self, attrs=None):
        self.attrs = dict(attrs or {})

    def check(self, program: Program) -> bool:
        """Applicability check (reference _check_self)."""
        return True

    def apply(self, main_program: Program, startup_program=None, context=None):
        if not self.check(main_program):
            raise RuntimeError(f"pass {self.name} not applicable")
        context = context or PassContext()
        self._apply_impl(main_program, startup_program, context)
        # invalidate compiled executors (cache keyed on the tape version;
        # the block is shared by clone() aliases so every alias recompiles)
        blk = main_program.global_block
        blk._version = getattr(blk, "_version", 0) + 1
        applied = context.attrs.setdefault("applied_passes", [])
        applied.append(self.name)
        return context

    def _apply_impl(self, main_program, startup_program, context):
        raise NotImplementedError


class PassManager:
    """reference: pass_base.py PassManager — ordered application."""

    def __init__(self, passes):
        self.passes = list(passes)
        self.context = PassContext()

    def apply(self, main_programs, startup_programs=None):
        mains = main_programs if isinstance(main_programs, (list, tuple)) \
            else [main_programs]
        starts = startup_programs or [None] * len(mains)
        for m, s in zip(mains, starts):
            for p in self.passes:
                p.apply(m, s, self.context)
        return self.context

    @property
    def names(self):
        return [p.name for p in self.passes]


def _use_counts(block):
    """How many ops read each Variable (by id) — fusion safety check."""
    counts: dict[int, int] = {}
    for op in block.ops:
        for t in _flat_inputs(op.inputs):
            if isinstance(t, Variable):
                counts[id(t)] = counts.get(id(t), 0) + 1
    return counts


def _cast_wrap(fn, src_dtype, dst_dtype):
    """Wrap an op lowering so floating inputs of `src_dtype` are cast to
    `dst_dtype` before the op runs — the one cast-policy closure shared by
    every mixed-precision pass (static AMP O2, auto_parallel_amp/fp16)."""

    def f(*ins):
        cast = [a.astype(dst_dtype)
                if hasattr(a, "dtype") and a.dtype == src_dtype else a
                for a in ins]
        return fn(*cast)

    return f


# -------------------------------------------------------------------- AMP O2
_AMP_WHITELIST = {
    "matmul", "matmul_v2", "linear", "conv2d", "conv1d", "conv3d", "einsum",
    "mul", "bmm", "addmm", "fused_gemm_epilogue",
}
_AMP_BLACKLIST = {
    "softmax", "log_softmax", "cross_entropy", "exp", "log", "mean",
    "reduce_mean", "sum", "reduce_sum", "layer_norm", "batch_norm",
    "logsumexp", "norm",
}


@register_pass("auto_mixed_precision")
class AMPO2Pass(PassBase):
    """Static AMP at O2 with master weights.

    Reference analog: fluid/contrib/mixed_precision/fp16_utils.py
    cast_model_to_fp16 + the master-weight machinery in the AMP optimizer.
    TPU-native: whitelist ops compute in bfloat16 (MXU-native); the Executor's
    parameter arrays stay fp32 — they ARE the master weights (the optimizer
    updates fp32; weights are cast at each use inside the compiled program,
    which XLA folds into a single cast per buffer per step).
    """

    def _apply_impl(self, main_program, startup_program, context):
        dtype = jnp.bfloat16 if self.attrs.get("dtype", "bfloat16") == \
            "bfloat16" else jnp.float16

        for block in main_program.blocks:
            for op in block.ops:
                if "amp" in op.attrs:
                    continue  # idempotent: the attr records the applied policy
                base = op.type.split("/")[-1]
                if base in _AMP_WHITELIST:
                    op.fn = _cast_wrap(op.fn, jnp.float32, dtype)
                    op.attrs["amp"] = "bf16"
                elif base in _AMP_BLACKLIST:
                    # force fp32 for numerically-sensitive ops
                    op.fn = _cast_wrap(op.fn, dtype, jnp.float32)
                    op.attrs["amp"] = "fp32"
        context.attrs["amp_dtype"] = jnp.dtype(dtype).name


# -------------------------------------------------------- fuse gemm epilogue
_EPILOGUE_ACTS = {"relu", "gelu", "tanh", "sigmoid"}


@register_pass("fuse_gemm_epilogue")
class FuseGemmEpiloguePass(PassBase):
    """Fuse matmul + add(bias) [+ activation] chains into one Operator.

    Reference analog: fuse_gemm_epilogue_pass.cc (cublasLt epilogues). On TPU
    XLA fuses the epilogue into the MXU matmul anyway — the value here is the
    PROGRAM-level annotation (profiler/bench attribution, and one tape node
    instead of three for replay/pass traversal), matching the reference's
    graph-level contract.
    """

    def _apply_impl(self, main_program, startup_program, context):
        n = [0]
        for block in main_program.blocks:
            _rewrite_chains(block, self._match, "fused_gemm_epilogue",
                            _use_counts(block), n, make_op=self._make_op,
                            pass_name=self.name)
        context.attrs["fused_gemm_epilogue"] = n[0]

    @staticmethod
    def _make_op(parts):
        mm, add = parts[0], parts[1]
        act = parts[2] if len(parts) > 2 else None
        mm_pos = next(
            j for j, t in enumerate(add.inputs)
            if isinstance(t, Variable) and id(t) == id(mm.outputs[0])
        )
        fused_fn = FuseGemmEpiloguePass._compose(mm, add, act, mm_pos)
        fused_inputs = list(mm.inputs) + [
            t for j, t in enumerate(add.inputs) if j != mm_pos
        ]
        return Operator(
            "fused_gemm_epilogue", fused_fn, fused_inputs, parts[-1].outputs,
            attrs={"epilogue": (act.type if act else "bias"),
                   "fused_from": [p.type for p in parts]},
            op_role=mm.op_role,
        )

    @staticmethod
    def _match(ops, i, counts):
        op = ops[i]
        if op.type.split("/")[-1] not in ("matmul", "matmul_v2", "mul"):
            return None
        if len(op.outputs) != 1 or counts.get(id(op.outputs[0]), 0) != 1:
            return None
        # the single consumer must be the next-op add with the matmul output
        nxt = next((o for o in ops[i + 1:]
                    if any(isinstance(t, Variable) and id(t) == id(op.outputs[0])
                           for t in _flat_inputs(o.inputs))), None)
        if nxt is None or nxt.type.split("/")[-1] not in ("add", "elementwise_add"):
            return None
        if len(nxt.outputs) != 1:
            return None
        act = None
        if counts.get(id(nxt.outputs[0]), 0) == 1:
            cand = next((o for o in ops
                         if any(isinstance(t, Variable)
                                and id(t) == id(nxt.outputs[0])
                                for t in _flat_inputs(o.inputs))), None)
            if cand is not None and cand.type.split("/")[-1] in _EPILOGUE_ACTS \
                    and len(cand.outputs) == 1:
                act = cand
        return [op, nxt] + ([act] if act else [])

    @staticmethod
    def _compose(mm, add, act, mm_pos):
        n_mm = len(mm.inputs)

        def fused(*ins):
            y = mm.fn(*ins[:n_mm])
            add_args = list(ins[n_mm:])
            add_args.insert(mm_pos, y)
            y = add.fn(*add_args)
            if act is not None:
                y = act.fn(y)
            return y

        return fused


# ----------------------------------------------- generic chain-pattern fusion
def _single_consumer(ops, out, counts):
    """The one op reading `out`, or None if shared/absent."""
    if counts.get(id(out), 0) != 1:
        return None
    return next((o for o in ops
                 if any(isinstance(t, Variable) and id(t) == id(out)
                        for t in _flat_inputs(o.inputs))), None)


def _compose_chain(parts):
    """One closure running `parts` in dataflow order. Returns (fn, ext_inputs):
    fn takes the chain's EXTERNAL inputs flattened in part order; each part's
    link input (the previous part's output) is threaded internally."""
    plan = []
    ext_inputs = []
    prev_out = None
    for p in parts:
        ins = list(p.inputs)
        link = next((j for j, t in enumerate(ins)
                     if prev_out is not None and isinstance(t, Variable)
                     and id(t) == id(prev_out)), None)
        plan.append((p.fn, link, len(ins)))
        ext_inputs.extend(t for j, t in enumerate(ins) if j != link)
        prev_out = p.outputs[0]

    def fused(*flat_ext):
        it = iter(flat_ext)
        y = None
        for fn, link, n_ins in plan:
            args = [y if j == link else next(it) for j in range(n_ins)]
            y = fn(*args)
        return y

    return fused, ext_inputs


def _scope_sig(op):
    """The scope tags a fused op must agree on (pipeline stage, fp16
    region) — chains mixing signatures are refused by _rewrite_chains."""
    return (op.attrs.get("device"), op.attrs.get("in_fp16_guard"))


def _rewrite_chains(block, match_fn, fused_type, counts, n_fused_box,
                    make_op=None, pass_name=None):
    """The fuse-rewrite loop shared by the pattern passes: fused op emitted at
    the LAST part's position (all pulled-in operands already defined —
    round-4 advisor finding on fuse_gemm_epilogue), interior parts dropped,
    chains claiming an already-consumed part refused. `make_op(parts)`
    overrides the default generic-compose Operator construction."""
    ops = block.ops
    i = 0
    new_ops = []
    consumed = set()
    emit_at = {}
    while i < len(ops):
        op = ops[i]
        if id(op) in consumed:
            i += 1
            continue
        if id(op) in emit_at:
            new_ops.append(emit_at.pop(id(op)))
            i += 1
            continue
        parts = match_fn(ops, i, counts)
        if parts is not None and any(
                id(p) in consumed or id(p) in emit_at for p in parts[1:]):
            parts = None
        if parts is not None and any(
                _scope_sig(p) != _scope_sig(parts[0]) for p in parts[1:]):
            # a chain spanning a pipeline-stage or fp16_guard boundary must
            # NOT fuse: an untagged fused op would erase the boundary (the
            # splitter would re-stage it; guard mode would un-cast it) —
            # refusing keeps every part's own tag visible to those passes
            parts = None
        if parts is None:
            new_ops.append(op)
            i += 1
            continue
        last = parts[-1]
        if make_op is not None:
            fused = make_op(parts)
        else:
            fused_fn, ext_inputs = _compose_chain(parts)
            fused = Operator(
                fused_type, fused_fn, ext_inputs, last.outputs,
                attrs={"fused_from": [p.type for p in parts]},
                op_role=parts[0].op_role,
            )
        # scope attrs other passes consume (pipeline stage, fp16 region)
        # survive fusion — the signature check above guarantees every part
        # carries the same values
        for key, val in zip(("device", "in_fp16_guard"), _scope_sig(parts[0])):
            if val is not None:
                fused.attrs.setdefault(key, val)
        emit_at[id(last)] = fused
        for p in parts[1:-1]:
            consumed.add(id(p))
        # interior outputs no longer exist in the program; fetching one at
        # run time would otherwise surface as a bare KeyError deep inside
        # lowering — Executor.run consults this map to name the pass (the
        # Variable is kept strongly so its id can't be recycled)
        fused_away = block.__dict__.setdefault("_fused_away", {})
        for p in parts[:-1]:
            for var in p.outputs:
                if isinstance(var, Variable):
                    fused_away[id(var)] = (var, pass_name or fused_type)
        n_fused_box[0] += 1
        i += 1
    block.ops = list(new_ops)


_MATMUL_TYPES = {"matmul", "matmul_v2", "bmm", "mul"}
_SCALE_TYPES = {"scale", "multiply", "elementwise_mul", "divide",
                "elementwise_div", "truediv", "div"}


@register_pass("fuse_attention")
class FuseAttentionPass(PassBase):
    """Collapse a hand-rolled attention chain into one `fused_attention` op.

    Pattern: matmul(QK^T) -> [scale]* -> softmax -> [dropout] -> matmul(.V).
    Reference analog: fused_attention_op.cc / the fuse_multihead_attention
    inference passes — there one CUDA kernel; here (like fuse_gemm_epilogue)
    the value is program-level: one tape node for profiler attribution and
    pass traversal, and loaded .pdmodel programs that hand-roll attention
    present a single recognizable op. XLA already fuses the HLO chain; the
    eager path routes native attention through the Pallas flash kernel
    (nn/functional sdpa), which this pass deliberately does not second-guess
    — the composed closures preserve the program's exact semantics.
    """

    def _apply_impl(self, main_program, startup_program, context):
        n = [0]
        for block in main_program.blocks:
            _rewrite_chains(block, self._match, "fused_attention",
                            _use_counts(block), n, pass_name=self.name)
        context.attrs["fused_attention"] = n[0]

    @staticmethod
    def _match(ops, i, counts):
        op = ops[i]
        if op.type.split("/")[-1] not in _MATMUL_TYPES or len(op.outputs) != 1:
            return None
        parts = [op]
        cur = op
        # optional scaling ops between QK^T and softmax
        for _ in range(2):
            nxt = _single_consumer(ops, cur.outputs[0], counts)
            if nxt is not None and nxt.type.split("/")[-1] in _SCALE_TYPES \
                    and len(nxt.outputs) == 1:
                parts.append(nxt)
                cur = nxt
            else:
                break
        sm = _single_consumer(ops, cur.outputs[0], counts)
        if sm is None or sm.type.split("/")[-1] != "softmax" \
                or len(sm.outputs) != 1:
            return None
        parts.append(sm)
        cur = sm
        drop = _single_consumer(ops, cur.outputs[0], counts)
        if drop is not None and drop.type.split("/")[-1] == "dropout" \
                and len(drop.outputs) == 1:
            parts.append(drop)
            cur = drop
        av = _single_consumer(ops, cur.outputs[0], counts)
        if av is None or av.type.split("/")[-1] not in _MATMUL_TYPES \
                or len(av.outputs) != 1:
            return None
        parts.append(av)
        return parts


_FFN_ACTS = {"gelu", "relu", "silu", "swish"}


@register_pass("fuse_feedforward")
class FuseFeedForwardPass(PassBase):
    """Collapse linear -> activation -> linear into one `fused_feedforward`.

    Reference analog: fused_feedforward_op.cc (one kernel for the transformer
    FFN block). Same program-level contract as fuse_gemm_epilogue: XLA fuses
    the HLO; the fused node is for attribution, traversal, and .pdmodel
    programs exported by frameworks that emit the fused op.
    """

    def _apply_impl(self, main_program, startup_program, context):
        n = [0]
        for block in main_program.blocks:
            _rewrite_chains(block, self._match, "fused_feedforward",
                            _use_counts(block), n, pass_name=self.name)
        context.attrs["fused_feedforward"] = n[0]

    @staticmethod
    def _match(ops, i, counts):
        op = ops[i]
        if op.type.split("/")[-1] not in ("linear", "fused_gemm_epilogue") \
                or len(op.outputs) != 1:
            return None
        act = _single_consumer(ops, op.outputs[0], counts)
        if act is None or act.type.split("/")[-1] not in _FFN_ACTS \
                or len(act.outputs) != 1:
            return None
        out = _single_consumer(ops, act.outputs[0], counts)
        if out is None or out.type.split("/")[-1] \
                not in ("linear", "fused_gemm_epilogue") \
                or len(out.outputs) != 1:
            return None
        return [op, act, out]


# ------------------------------------------------- classic IR rewrite passes
# XLA performs HLO-level fold/DCE/CSE inside each compiled computation; these
# program-level versions exist for the same reasons the reference keeps them
# as ir passes (constant_folding / graph memory passes / Executor prune,
# executor.py:1358): a smaller tape traces and compiles faster, prune defines
# the export subgraph, and pass-composition tests need observable rewrites.

_STOCHASTIC_TYPES = ("dropout", "rand", "uniform", "gauss", "noise",
                     "bernoulli", "multinomial", "py_func", "print", "while",
                     "cond")


def _is_stochastic(op_type: str) -> bool:
    t = op_type.split("/")[-1].lower()
    return any(s in t for s in _STOCHASTIC_TYPES)


@register_pass("constant_folding")
class ConstantFoldingPass(PassBase):
    """Evaluate ops whose inputs are all compile-time constants and replace
    them with materialized constants (reference: the inference-analysis
    constant-fold family in paddle/fluid/framework/ir/; the IPU path folds
    via popart patterns). In this IR creation ops (full/arange/...) evaluate
    eagerly at trace time, so constants enter the tape as frozen
    (stop_gradient) Tensors: those fold, and folding propagates through
    Variables transitively. Trainable Tensors never fold. Like the
    reference's pass this freezes CURRENT values — apply to inference/
    export programs, not to programs whose frozen tensors (e.g. BN running
    stats) still mutate. attrs: max_elems (default 1<<20) bounds
    materialized size; fold_frozen_tensors=False restricts folding to
    Variable chains only."""

    def _apply_impl(self, main_program, startup_program, context):
        max_elems = int(self.attrs.get("max_elems", 1 << 20))
        fold_frozen = bool(self.attrs.get("fold_frozen_tensors", True))
        block = main_program.global_block
        fold_env: dict[int, object] = {}
        n_folded = 0
        new_ops = []
        for op in block.ops:
            foldable = not _is_stochastic(op.type) and not op.attrs.get(
                "no_fold", False)
            concrete = []
            if foldable:
                for t in op.inputs:
                    v = _try_concrete(t, fold_env, fold_frozen)
                    if v is _NOT_CONST:
                        foldable = False
                        break
                    concrete.append(v)
            if foldable:
                try:
                    out = op.fn(*concrete)
                except Exception:
                    new_ops.append(op)
                    continue
                outs = list(out) if isinstance(out, (tuple, list)) else [out]
                if any(getattr(o, "size", 0) > max_elems for o in outs):
                    new_ops.append(op)
                    continue
                for var, val in zip(op.outputs, outs):
                    fold_env[id(var)] = val
                vals = tuple(outs)
                new_ops.append(Operator(
                    "folded_constant", lambda _v=vals: _v if len(_v) > 1
                    else _v[0], [], op.outputs,
                    attrs={"folded_from": op.type}, op_role=op.op_role))
                n_folded += 1
            else:
                new_ops.append(op)
        block.ops[:] = new_ops
        context.attrs["constant_folding.n_folded"] = n_folded


_NOT_CONST = object()


def _try_concrete(t, fold_env, fold_frozen):
    """Concrete value of an op input at fold time, or _NOT_CONST."""
    if isinstance(t, Variable):
        return fold_env.get(id(t), _NOT_CONST)
    if isinstance(t, (list, tuple)):
        vals = [_try_concrete(i, fold_env, fold_frozen) for i in t]
        if any(v is _NOT_CONST for v in vals):
            return _NOT_CONST
        return type(t)(vals)
    from ..core.tensor import Tensor

    if isinstance(t, Tensor):
        # frozen tensors are constants from this program's point of view;
        # trainables update every step and must stay live inputs
        if fold_frozen and t.stop_gradient:
            return t._value
        return _NOT_CONST
    return t  # python scalar / shape tuple / dtype string


@register_pass("dead_code_elimination")
class DeadCodeEliminationPass(PassBase):
    """Remove ops not on any path to the given targets (reference:
    Executor._prune_program, python/paddle/fluid/executor.py:1358-1384 —
    prune-by-fetch-targets; ir memory_optimize family). attrs: targets —
    list of Variables (or names) that must stay computable. Side-effecting
    ops (collectives, send/recv, py_func, print) are always kept."""

    # collective ops by prefix; the rest by exact type match (substring
    # matching kept e.g. any "*fc_*" fused op alive and silently weakened DCE)
    _KEEP_PREFIXES = ("c_", "send", "recv", "partial_send", "partial_recv")
    _KEEP_EXACT = frozenset({"py_func", "print", "barrier",
                             "global_scatter", "global_gather"})

    def check(self, program):
        return bool(self.attrs.get("targets"))

    def _apply_impl(self, main_program, startup_program, context):
        block = main_program.global_block
        targets = self.attrs["targets"]
        live: set[int] = set()
        for t in targets:
            if isinstance(t, str):
                t = block.var(t)
            live.add(id(t))
        kept = []
        for op in reversed(block.ops):
            t = op.type.split("/")[-1].lower()
            keep = t.startswith(self._KEEP_PREFIXES) \
                or t in self._KEEP_EXACT \
                or any(id(o) in live for o in op.outputs)
            if keep:
                kept.append(op)
                for i in _flat_inputs(op.inputs):
                    if isinstance(i, Variable):
                        live.add(id(i))
            else:
                continue
        removed = len(block.ops) - len(kept)
        block.ops[:] = list(reversed(kept))
        context.attrs["dead_code_elimination.n_removed"] = removed


def _fn_fingerprint(fn):
    """Semantic fingerprint of an op lowering: code object + captured static
    config. Each op call builds a fresh closure over its kwargs (axis,
    keepdim, shapes, ...), most of which are NOT mirrored into op.attrs —
    keying on (type, inputs, attrs) alone would merge e.g. sum(x, axis=0)
    with sum(x, axis=1). Returns None (= never dedupe) when a captured cell
    cannot be fingerprinted safely."""
    import functools

    if isinstance(fn, functools.partial):
        inner = _fn_fingerprint(fn.func)
        if inner is None:
            return None
        return (inner, tuple(repr(a) for a in fn.args),
                tuple(sorted((k, repr(v)) for k, v in fn.keywords.items())))
    code = getattr(fn, "__code__", None)
    if code is None:
        # module-level callables (jnp.exp, jax.nn.relu — PjitFunctions with
        # no python code object): the object itself is the op; identity is a
        # sound key because there is no per-call captured config
        return ("obj", id(fn))
    cells = []
    for c in fn.__closure__ or ():
        try:
            v = c.cell_contents
        except ValueError:  # empty cell
            return None
        v = _value_fp(v)
        if v is None:
            return None
        cells.append(v)
    # defaults carry config too: folded_constant lambdas bind their value as
    # a default arg (`lambda _v=vals: ...`) — ignoring them merged distinct
    # constants (code-review r4, confirmed miscompile)
    defaults = []
    for v in list(fn.__defaults__ or ()) + sorted(
            (fn.__kwdefaults__ or {}).items()):
        v = _value_fp(v)
        if v is None:
            return None
        defaults.append(v)
    return (id(code), tuple(cells), tuple(defaults))


def _value_fp(v):
    """Fingerprint one captured value, or None when not provably static.
    Arrays hash by CONTENT — numpy's repr truncates large arrays with '...',
    which would collide distinct values."""
    import hashlib

    if callable(v):
        if getattr(v, "__closure__", None) is None \
                and getattr(v, "__code__", None) is not None:
            return ("fn", id(v.__code__))
        if getattr(v, "__code__", None) is None:
            return ("obj", id(v))  # module-level singleton (jnp.exp)
        return None  # nested closure: config may hide another level down
    if isinstance(v, (tuple, list)):
        parts = [_value_fp(i) for i in v]
        if any(p is None for p in parts):
            return None
        return (type(v).__name__, tuple(parts))
    if hasattr(v, "dtype") and hasattr(v, "shape"):
        try:
            arr = np.asarray(v)
        except Exception:
            return None  # tracer/abstract value
        return ("arr", str(arr.dtype), tuple(arr.shape),
                hashlib.sha1(arr.tobytes()).hexdigest())
    r = repr(v)
    if len(r) > 512 or " object at 0x" in r:
        return None  # opaque capture: not provably static config
    return r


@register_pass("common_subexpression_elimination")
class CSEPass(PassBase):
    """Deduplicate ops with identical (type, inputs, attrs, lowering
    fingerprint) (the classic ir CSE; XLA re-does this at HLO level, but a
    deduped tape traces faster and pass tests can observe it). The lowering
    fingerprint (code object + captured static kwargs) guards against
    merging ops whose config lives only in the closure. The duplicate is
    replaced by a zero-cost share op aliasing the first op's outputs, so
    Variables the user holds (fetch targets) stay defined."""

    def _apply_impl(self, main_program, startup_program, context):
        block = main_program.global_block
        seen: dict[tuple, Operator] = {}
        n_deduped = 0
        new_ops = []
        for op in block.ops:
            fp = _fn_fingerprint(op.fn)
            if _is_stochastic(op.type) or len(op.outputs) == 0 or fp is None:
                new_ops.append(op)
                continue
            key = (op.type, fp,
                   tuple(id(t) if isinstance(t, (Variable,)) or
                         hasattr(t, "_value") else repr(t)
                         for t in _flat_inputs(op.inputs)),
                   repr(sorted((k, repr(v)) for k, v in op.attrs.items())))
            first = seen.get(key)
            if first is not None and len(first.outputs) == len(op.outputs):
                new_ops.append(Operator(
                    "share", lambda *xs: xs if len(xs) > 1 else xs[0],
                    list(first.outputs), op.outputs,
                    attrs={"shared_from": first.type}, op_role=op.op_role))
                n_deduped += 1
            else:
                seen[key] = op
                new_ops.append(op)
        block.ops[:] = new_ops
        context.attrs["cse.n_deduped"] = n_deduped
