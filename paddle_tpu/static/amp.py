"""paddle.static.amp (reference: python/paddle/static/amp/__init__.py —
re-exports of fluid.contrib.mixed_precision). TPU-native: the static AMP
rewrite lives in the registered program passes (static/passes.py
auto_mixed_precision, distributed/passes.py auto_parallel_amp/fp16); this
namespace keeps the reference's static-AMP entry points working on top of
them.
"""
from __future__ import annotations

import contextlib

__all__ = ["decorate", "CustomOpLists", "AutoMixedPrecisionLists",
           "fp16_guard", "cast_model_to_fp16", "cast_parameters_to_fp16",
           "bf16"]


class AutoMixedPrecisionLists:
    """reference: fluid/contrib/mixed_precision/fp16_lists.py — white/black
    op lists consulted by the AMP passes."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        from .passes import _AMP_WHITELIST

        self.white_list = set(_AMP_WHITELIST) | set(custom_white_list or ())
        self.black_list = set(custom_black_list or ())
        self.black_varnames = set(custom_black_varnames or ())


CustomOpLists = AutoMixedPrecisionLists


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_pure_fp16=False,
             use_fp16_guard=None, use_bf16=False):
    """reference: mixed_precision/decorator.py decorate — wrap an optimizer
    so minimize() applies the AMP program rewrite. Here: minimize registers
    the train spec as usual, then the amp (O1) or fp16 (O2 + loss scaling)
    pass is applied to the program, composing with any other passes."""
    from ..distributed.passes import new_pass

    class _AmpOptimizer:
        def __init__(self, inner):
            self._inner = inner
            self._loss_scaling = float(init_loss_scaling)

        def minimize(self, loss, startup_program=None, parameters=None,
                     no_grad_set=None):
            out = self._inner.minimize(loss, startup_program=startup_program,
                                       parameters=parameters)
            from .program import default_main_program

            prog = default_main_program()
            if use_pure_fp16:
                # reference decorator.py:632: use_fp16_guard defaults to
                # use_pure_fp16 — but ONLY honor guard mode when the traced
                # program actually contains guarded ops; a guard-free script
                # under the reference default would silently train in fp32,
                # which the pass itself warns about. Explicit True/False is
                # passed through untouched.
                guard = use_fp16_guard
                if guard is None:
                    guard = any(
                        op.attrs.get("in_fp16_guard")
                        for block in prog.blocks for op in block.ops)
                new_pass("auto_parallel_fp16", {
                    "init_loss_scaling": init_loss_scaling,
                    "incr_every_n_steps": incr_every_n_steps,
                    "decr_every_n_nan_or_inf": decr_every_n_nan_or_inf,
                    "incr_ratio": incr_ratio, "decr_ratio": decr_ratio,
                    "use_bf16": use_bf16,
                    "use_fp16_guard": guard,
                    "use_dynamic_loss_scaling": use_dynamic_loss_scaling,
                }).apply(prog)
            else:
                new_pass("auto_parallel_amp", {
                    "custom_white_list":
                        sorted(amp_lists.white_list) if amp_lists else None,
                    "custom_black_list":
                        sorted(amp_lists.black_list) if amp_lists else None,
                }).apply(prog)
            return out

        def amp_init(self, place=None, scope=None, test_program=None,
                     use_fp16_test=False):
            """reference: decorator.py amp_init — master-weight cast point;
            parameter layout is the executor's job on this runtime."""

        def get_loss_scaling(self):
            return self._loss_scaling

        def __getattr__(self, name):
            return getattr(self.__dict__["_inner"], name)

    return _AmpOptimizer(optimizer)


@contextlib.contextmanager
def fp16_guard():
    """reference: fp16_utils.py fp16_guard — ops recorded inside this scope
    are the ONLY ones the pure-fp16 pass casts to low precision when
    use_fp16_guard is on (region-scoped O2; everything outside keeps fp32).
    Under dygraph there is no recording, so the scope is inert — use
    paddle.amp.auto_cast there."""
    from .program import fp16_guard_scope

    with fp16_guard_scope():
        yield


def cast_model_to_fp16(program, amp_lists=None, use_fp16_guard=True):
    """reference: fp16_utils.py cast_model_to_fp16 — apply the O2 cast
    rewrite to `program`; with use_fp16_guard only fp16_guard regions cast."""
    from ..distributed.passes import new_pass

    new_pass("auto_parallel_fp16",
             {"use_dynamic_loss_scaling": False,
              "use_fp16_guard": use_fp16_guard}).apply(program)
    return program


def cast_parameters_to_fp16(place, program, scope=None, to_fp16_var_names=None):
    """reference: fp16_utils.py cast_parameters_to_fp16. Parameters live as
    captured tensors; cast them in place."""
    import jax.numpy as jnp

    for p in program.captured_params():
        if p._value.dtype == jnp.float32 and not p.stop_gradient:
            p._value = p._value.astype(jnp.float16)


class _Bf16Namespace:
    """reference: mixed_precision/bf16 — bf16 variants. bf16 is the
    DEFAULT low precision on TPU; decorate_bf16 routes to the same passes
    with use_bf16."""

    AutoMixedPrecisionListsBF16 = AutoMixedPrecisionLists

    @staticmethod
    def decorate_bf16(optimizer, amp_lists=None, use_pure_bf16=False,
                      use_bf16_guard=None):
        return decorate(optimizer, amp_lists=amp_lists,
                        use_pure_fp16=use_pure_bf16, use_bf16=True,
                        use_fp16_guard=use_bf16_guard,
                        use_dynamic_loss_scaling=False)

    @staticmethod
    @contextlib.contextmanager
    def bf16_guard():
        from .program import fp16_guard_scope

        with fp16_guard_scope():
            yield


bf16 = _Bf16Namespace()
