"""Static pipeline parallelism: device_guard program splitting + a
SectionWorker-style micro-batch schedule.

Reference analog: PipelineOptimizer
(/root/reference/python/paddle/fluid/optimizer.py:4323 — ~1.5k lines of
program surgery cutting a static program at device_guard boundaries) executed
by SectionWorker (/root/reference/paddle/fluid/framework/device_worker.h:620)
per stage with micro-batch scopes.

TPU-native: the op tape is already a linear program, so the splitter is a
segmentation of `block.ops` by their `device` attr. Each stage segment becomes
a pure jitted function (params_seg, boundary_in, feeds) -> boundary_out placed
on its own device; the runner schedules micro-batches GPipe-style — forward
through all stages per micro-batch (XLA async dispatch overlaps stages across
devices), per-stage VJPs in reverse, gradient accumulation across
micro-batches, one optimizer step. Cross-stage transfers are device_puts
(send_v2/recv_v2 analog — same contract as fleet/pipeline_parallel._xfer).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as rng_mod
from ..core import tape as tape_mod
from ..core.errors import InvalidArgumentError
from ..core.tensor import Tensor
from .program import Program, Variable, _flat_inputs


def split_program_by_device(program: Program):
    """Segment the top-level op tape at device_guard boundaries.

    Returns [(device_tag, [ops])] in program order. Ops without a device attr
    join the current segment (reference: PipelineOptimizer assigns unannotated
    ops to the previous device)."""
    segments = []
    cur_dev, cur_ops = None, []
    started = False
    for op in program.global_block.ops:
        dev = op.attrs.get("device", None)
        if not started:
            cur_dev = dev
            started = True
        if dev is not None and dev != cur_dev:
            segments.append((cur_dev, cur_ops))
            cur_dev, cur_ops = dev, []
        cur_ops.append(op)
    if cur_ops:
        segments.append((cur_dev, cur_ops))
    return segments


class PipelineCompiledProgram:
    """Compile a device_guard-annotated program into per-stage functions and
    run micro-batched training steps (the SectionWorker loop).

    Usage:
        pipe = PipelineCompiledProgram(main, loss, optimizer,
                                       num_microbatches=4)
        loss_val = pipe.run(feed={"x": ..., "label": ...})
    """

    def __init__(self, program: Program, loss: Variable, optimizer=None,
                 num_microbatches: int = 1, devices=None):
        self.program = program
        self.loss_var = loss
        self.optimizer = optimizer
        self.num_microbatches = int(num_microbatches)
        self.segments = split_program_by_device(program)
        if len(self.segments) < 2:
            raise InvalidArgumentError(
                "pipeline needs >= 2 device_guard stages; got "
                f"{len(self.segments)} — annotate ops with static.device_guard")
        n = len(self.segments)
        avail = devices if devices is not None else jax.devices()
        self.stage_devices = [avail[min(i, len(avail) - 1)] for i in range(n)]
        self._analyze()
        # place each stage's params on its device once (SectionWorker scope
        # ownership); the jitted stage fn then runs where its operands live
        for s, params in enumerate(self.stage_params):
            for p in params:
                p._value = jax.device_put(p._value, self.stage_devices[s])
        self._build_stage_fns()
        self._opt_state = None

    # ------------------------------------------------------------- analysis
    def _analyze(self):
        """Per segment: captured params, feed vars, boundary ins/outs."""
        produced_by = {}
        for s, (_, ops) in enumerate(self.segments):
            for op in ops:
                for o in op.outputs:
                    produced_by[id(o)] = s
        self.stage_params = []
        self.stage_feeds = []
        self.stage_bins = []  # boundary inputs: [(var, producer_stage)]
        feed_names = {v.name for v in self.program._data_vars}
        for s, (_, ops) in enumerate(self.segments):
            params, feeds, bins = [], [], []
            seen = set()
            local = {id(o) for op in ops for o in op.outputs}
            for op in ops:
                for t in _flat_inputs(op.inputs):
                    if id(t) in seen:
                        continue
                    seen.add(id(t))
                    if isinstance(t, Variable):
                        if id(t) in local:
                            continue
                        if t.name in feed_names:
                            feeds.append(t)
                        elif id(t) in produced_by and produced_by[id(t)] < s:
                            bins.append((t, produced_by[id(t)]))
                        else:
                            raise InvalidArgumentError(
                                f"stage {s} reads {t.name} produced in a LATER "
                                "stage — device_guard order must follow "
                                "dataflow")
                    elif isinstance(t, Tensor) and not isinstance(t, Variable):
                        params.append(t)
            self.stage_params.append(params)
            self.stage_feeds.append(feeds)
            self.stage_bins.append(bins)
        # boundary outputs of each stage = vars consumed by later stages + loss
        self.stage_bouts = [[] for _ in self.segments]
        for s, bins in enumerate(self.stage_bins):
            for var, src in bins:
                if var not in self.stage_bouts[src]:
                    self.stage_bouts[src].append(var)
        last = len(self.segments) - 1
        if id(self.loss_var) not in {
            id(o) for _, ops in self.segments[last:] for op in ops
            for o in op.outputs
        }:
            raise InvalidArgumentError("loss must be produced by the last stage")
        if self.loss_var not in self.stage_bouts[last]:
            self.stage_bouts[last].append(self.loss_var)

    # ----------------------------------------------------------- stage fns
    def _build_stage_fns(self):
        self._fwd_fns = []
        for s, (_, ops) in enumerate(self.segments):
            feeds = self.stage_feeds[s]
            bins = self.stage_bins[s]
            bouts = self.stage_bouts[s]
            params = self.stage_params[s]

            def fwd(param_arrays, bin_arrays, feed_arrays, key, _ops=ops,
                    _feeds=feeds, _bins=bins, _bouts=bouts, _params=params):
                env = {id(t): a for t, a in zip(_params, param_arrays)}
                env.update({id(v): a for (v, _), a in zip(_bins, bin_arrays)})
                env.update({id(v): a for v, a in zip(_feeds, feed_arrays)})

                def resolve(x):
                    if isinstance(x, (Variable, Tensor)):
                        if id(x) in env:
                            return env[id(x)]
                        if isinstance(x, Variable):
                            raise KeyError(f"unbound var {x.name}")
                        return x._value
                    if isinstance(x, (list, tuple)):
                        return type(x)(resolve(i) for i in x)
                    return x

                with tape_mod.no_grad(), rng_mod.trace_rng_scope(key):
                    for op in _ops:
                        out = op.fn(*[resolve(i) for i in op.inputs])
                        outs = list(out) if isinstance(out, (tuple, list)) else [out]
                        for var, val in zip(op.outputs, outs):
                            env[id(var)] = val
                return [env[id(v)] for v in _bouts]

            self._fwd_fns.append(jax.jit(fwd))

    # ------------------------------------------------------------- running
    def run(self, feed: dict, fetch_list=None):
        """One training step: micro-batch forward/backward over the stages,
        grad accumulation, optimizer update. Returns the mean loss."""
        mb = self.num_microbatches
        feeds_split = {}
        for k, v in feed.items():
            a = np.asarray(v.numpy() if isinstance(v, Tensor) else v)
            if a.shape[0] % mb:
                raise InvalidArgumentError(
                    f"feed {k!r} batch {a.shape[0]} not divisible by "
                    f"{mb} micro-batches")
            feeds_split[k] = np.split(a, mb)

        params_flat = [p for ps in self.stage_params for p in ps]
        train_idx = [i for i, p in enumerate(params_flat) if not p.stop_gradient]

        def whole(train_arrays, feed_map, key):
            """The chained pipeline as one function of trainable params —
            per-stage fns keep per-device placement; jax.vjp over the chain
            gives the stage backward (SectionWorker backward sections)."""
            arrays = [p._value for p in params_flat]
            for i, a in zip(train_idx, train_arrays):
                arrays[i] = a
            off = 0
            per_stage = []
            for ps in self.stage_params:
                per_stage.append(arrays[off : off + len(ps)])
                off += len(ps)
            bouts_env = {}
            for s in range(len(self.segments)):
                # inter-stage transfer: the send_v2/recv_v2 analog
                bin_arrays = [
                    jax.device_put(bouts_env[id(v)], self.stage_devices[s])
                    for v, _ in self.stage_bins[s]
                ]
                feed_arrays = [feed_map[v.name] for v in self.stage_feeds[s]]
                outs = self._fwd_fns[s](per_stage[s], bin_arrays, feed_arrays, key)
                for v, a in zip(self.stage_bouts[s], outs):
                    bouts_env[id(v)] = a
            loss_val = bouts_env[id(self.loss_var)]
            if hasattr(loss_val, "ndim") and loss_val.ndim > 0:
                loss_val = jnp.mean(loss_val)
            return loss_val.astype(jnp.float32)

        accum = None
        losses = []
        for m in range(mb):
            feed_arrays_map = {k: jnp.asarray(v[m]) for k, v in feeds_split.items()}
            ta = [params_flat[i]._value for i in train_idx]
            if self.optimizer is None:
                losses.append(whole(ta, feed_arrays_map, rng_mod.next_rng_key()))
                continue
            loss_m, grads = jax.value_and_grad(whole)(
                ta, feed_arrays_map, rng_mod.next_rng_key())
            losses.append(loss_m)
            accum = grads if accum is None else [a + g for a, g in zip(accum, grads)]
        if self.optimizer is not None and accum is not None:
            opt = self.optimizer
            pd = {str(i): params_flat[i]._value for i in train_idx}
            gd = {str(i): g / mb for i, g in zip(train_idx, accum)}
            if self._opt_state is None:
                self._opt_state = opt.functional_init(pd)
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            new_p, self._opt_state = opt.functional_update(
                pd, gd, self._opt_state, lr)
            for i in train_idx:
                params_flat[i]._value = new_p[str(i)]
        return float(np.mean([np.asarray(l) for l in losses]))
