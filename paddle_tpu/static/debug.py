"""Program debugging/visualization.

Reference analog: ProgramDesc DebugString (proto text dump used everywhere in
the reference's error messages), fluid/graphviz.py + ir/graph_viz_pass.cc
(.dot dumps of the op graph).
"""
from __future__ import annotations

from .program import OpRole, Program, Variable, _flat_inputs

__all__ = ["program_to_string", "program_to_dot"]

_ROLE_NAMES = {OpRole.Forward: "Forward", OpRole.Backward: "Backward",
               OpRole.Optimize: "Optimize", OpRole.RPC: "RPC",
               OpRole.Dist: "Dist", OpRole.LRSched: "LRSched",
               OpRole.Loss: "Loss"}


def _var_sig(v):
    if isinstance(v, Variable):
        return f"{v.name}:{v.dtype}{list(v.shape)}"
    shape = list(getattr(v, "shape", []) or [])
    return f"<const>:{getattr(v, 'dtype', '?')}{shape}"


def program_to_string(program: Program) -> str:
    """Readable dump of every block/op: types, in/out var signatures, role,
    device/attr annotations (the DebugString analog)."""
    lines = []
    for bi, block in enumerate(program.blocks):
        lines.append(f"block {bi} ({len(block.ops)} ops):")
        for i, op in enumerate(block.ops):
            ins = ", ".join(_var_sig(t) for t in _flat_inputs(op.inputs)
                            if hasattr(t, "shape"))
            outs = ", ".join(_var_sig(o) for o in op.outputs)
            role = _ROLE_NAMES.get(op.op_role, str(op.op_role))
            extras = ""
            show_attrs = {k: v for k, v in op.attrs.items()
                          if isinstance(v, (str, int, float, bool))}
            if show_attrs:
                extras = " " + ", ".join(f"{k}={v}" for k, v in
                                         sorted(show_attrs.items()))
            lines.append(f"  [{i:3d}] {op.type}({ins}) -> {outs}"
                         f"  {{role={role}{extras}}}")
    return "\n".join(lines)


def program_to_dot(program: Program, name="program") -> str:
    """Graphviz .dot of the dataflow (op nodes + var edges) — the
    graph_viz_pass analog; render with `dot -Tsvg`."""
    lines = [f"digraph {name} {{", "  rankdir=TB;",
             '  node [shape=box, fontsize=10];']
    var_nodes = {}

    def var_node(v):
        key = id(v)
        if key not in var_nodes:
            var_nodes[key] = f"var{len(var_nodes)}"
            label = _var_sig(v).replace('"', "'")
            lines.append(
                f'  {var_nodes[key]} [label="{label}", shape=ellipse, '
                'fontsize=9, color=gray50];')
        return var_nodes[key]

    n = 0
    for block in program.blocks:
        for op in block.ops:
            op_id = f"op{n}"
            n += 1
            dev = op.attrs.get("device")
            color = "lightblue" if dev is None else "palegreen"
            label = op.type + (f"\\n@{dev}" if dev else "")
            lines.append(f'  {op_id} [label="{label}", style=filled, '
                         f'fillcolor={color}];')
            for t in _flat_inputs(op.inputs):
                if isinstance(t, Variable):
                    lines.append(f"  {var_node(t)} -> {op_id};")
            for o in op.outputs:
                lines.append(f"  {op_id} -> {var_node(o)};")
    lines.append("}")
    return "\n".join(lines)
