"""paddle.static — static-graph front end (reference: python/paddle/static/).

TPU-native static graph = the IPU whole-graph compile model (survey §3.5): build a
Program IR, lower the WHOLE program to one XLA computation, execute via a single
runtime call with buffers resident on device. See program.py / executor.py.
"""
from .mode import disable_static, enable_static, in_static_mode  # noqa: F401
from .program import (  # noqa: F401
    Block,
    Operator,
    Program,
    Variable,
    data,
    default_main_program,
    default_startup_program,
    device_guard,
    global_scope,
    name_scope,
    program_guard,
)
from .pipeline import PipelineCompiledProgram, split_program_by_device  # noqa: F401
from . import amp  # noqa: F401
from .debug import program_to_dot, program_to_string  # noqa: F401
from .scope import Scope, scope_guard  # noqa: F401
from .executor import CompiledProgram, Executor  # noqa: F401
from .control_flow import case, cond, switch_case, while_loop  # noqa: F401
from .io import (  # noqa: F401
    load_inference_model,
    save_inference_model,
    serialize_program,
)
from ..jit import InputSpec  # noqa: F401
from .extras import (  # noqa: F401
    BuildStrategy,
    ExecutionStrategy,
    ExponentialMovingAverage,
    GradVariable,
    IpuCompiledProgram,
    IpuStrategy,
    ParallelExecutor,
    Print,
    WeightNormParamAttr,
    accuracy,
    append_backward,
    auc,
    create_global_var,
    create_parameter,
    deserialize_persistables,
    deserialize_program,
    gradients,
    ipu_shard_guard,
    load,
    load_from_file,
    load_program_state,
    mlu_places,
    normalize_program,
    npu_places,
    py_func,
    save,
    save_to_file,
    serialize_persistables,
    set_ipu_shard,
    set_program_state,
)
from . import nn  # noqa: F401
from . import passes  # noqa: F401
from .passes import PassBase, PassContext, PassManager, new_pass, register_pass  # noqa: F401


def cpu_places(device_count=None):
    from ..core.place import CPUPlace

    return [CPUPlace()]


def tpu_places(device_ids=None):
    from ..core.place import TPUPlace

    import jax

    n = jax.device_count()
    ids = device_ids if device_ids is not None else range(n)
    return [TPUPlace(i) for i in ids]


cuda_places = tpu_places
xpu_places = tpu_places
